"""Time-series tier on the mesh: asof joins and tumbling/hopping windows run
SPMD (hash-shuffle by symbol over all_to_all, per-shard sort+scan kernels —
parallel/mesh_exec.mesh_asof / mesh_window_agg) and must equal the embedded
engine's streaming executors.  Session/sliding windows and by-less asof fall
back to the engine — LOUDLY (ctx.last_mesh_fallback records why)."""

import numpy as np
import pandas as pd
import pytest

from quokka_tpu import QuokkaContext
from quokka_tpu.parallel.mesh import make_mesh
from quokka_tpu.windows import HoppingWindow, SessionWindow, TumblingWindow

from test_timeseries import make_ticks


@pytest.fixture(scope="module")
def ticks(tmp_path_factory):
    import pyarrow.parquet as pq

    root = tmp_path_factory.mktemp("mesh_ticks")
    trades, quotes = make_ticks()
    tp, qp = str(root / "trades.parquet"), str(root / "quotes.parquet")
    pq.write_table(trades, tp, row_group_size=512)
    pq.write_table(quotes, qp, row_group_size=512)
    return tp, qp, trades.to_pandas(), quotes.to_pandas()


def _contexts():
    return QuokkaContext(), QuokkaContext(mesh=make_mesh(8))


def _streams(ctx, tp, qp):
    t = ctx.read_sorted_parquet(tp, sorted_by="time")
    q = ctx.read_sorted_parquet(qp, sorted_by="time")
    return t, q


def _norm(df, keys):
    return df.sort_values(keys).reset_index(drop=True)


class TestMeshAsof:
    @pytest.mark.parametrize("direction", ["backward", "forward"])
    def test_asof_matches_engine(self, ticks, direction):
        tp, qp, tdf, qdf = ticks
        plain, mesh = _contexts()
        t, q = _streams(plain, tp, qp)
        exp = t.join_asof(q, on="time", by="symbol", direction=direction).collect()
        t, q = _streams(mesh, tp, qp)
        got = t.join_asof(q, on="time", by="symbol", direction=direction).collect()
        assert mesh.last_mesh_fallback is None, mesh.last_mesh_fallback
        keys = ["symbol", "time", "size"]
        exp, got = _norm(exp, keys), _norm(got, keys)
        assert list(got.columns) == list(exp.columns)
        pd.testing.assert_frame_equal(got, exp, check_dtype=False)

    def test_asof_then_agg(self, ticks):
        tp, qp, tdf, qdf = ticks
        plain, mesh = _contexts()

        def agg(ctx):
            t, q = _streams(ctx, tp, qp)
            return (
                t.join_asof(q, on="time", by="symbol")
                .groupby("symbol")
                .agg_sql("sum(size) as total_size, count(*) as n")
                .collect()
            )

        exp = agg(plain)
        got = agg(mesh)
        assert mesh.last_mesh_fallback is None, mesh.last_mesh_fallback
        pd.testing.assert_frame_equal(
            _norm(got, ["symbol"]), _norm(exp, ["symbol"]), check_dtype=False
        )

    def test_byless_asof_falls_back_loudly(self, ticks):
        tp, qp, tdf, qdf = ticks
        plain, mesh = _contexts()
        t, q = _streams(plain, tp, qp)
        exp = t.join_asof(q, on="time").collect()
        t, q = _streams(mesh, tp, qp)
        got = t.join_asof(q, on="time").collect()
        assert mesh.last_mesh_fallback is not None
        assert "asof" in mesh.last_mesh_fallback
        keys = ["time", "size"]
        pd.testing.assert_frame_equal(
            _norm(got, keys), _norm(exp, keys), check_dtype=False
        )


class TestMeshWindows:
    def test_tumbling_matches_engine(self, ticks):
        tp, qp, tdf, qdf = ticks
        plain, mesh = _contexts()
        t, _ = _streams(plain, tp, qp)
        exp = t.window_agg(
            TumblingWindow(10_000),
            "sum(size) as total, count(*) as n, avg(size) as mean_sz",
            by="symbol",
        ).collect()
        t, _ = _streams(mesh, tp, qp)
        got = t.window_agg(
            TumblingWindow(10_000),
            "sum(size) as total, count(*) as n, avg(size) as mean_sz",
            by="symbol",
        ).collect()
        assert mesh.last_mesh_fallback is None, mesh.last_mesh_fallback
        keys = ["symbol", "window_start"]
        exp, got = _norm(exp, keys), _norm(got, keys)
        assert list(got.columns) == list(exp.columns)
        pd.testing.assert_frame_equal(got, exp, check_dtype=False)

    def test_hopping_matches_engine(self, ticks):
        tp, qp, tdf, qdf = ticks
        plain, mesh = _contexts()
        t, _ = _streams(plain, tp, qp)
        exp = t.window_agg(
            HoppingWindow(20_000, 10_000), "count(*) as n, sum(size) as total",
            by="symbol",
        ).collect()
        t, _ = _streams(mesh, tp, qp)
        got = t.window_agg(
            HoppingWindow(20_000, 10_000), "count(*) as n, sum(size) as total",
            by="symbol",
        ).collect()
        assert mesh.last_mesh_fallback is None, mesh.last_mesh_fallback
        keys = ["symbol", "window_start"]
        pd.testing.assert_frame_equal(
            _norm(got, keys), _norm(exp, keys), check_dtype=False
        )

    def test_fine_hop_falls_back_loudly(self, ticks):
        # replication factor size//hop above the cap must leave the mesh
        # (static whole-dataset blowup inside one shard_map), not OOM it
        tp, qp, tdf, qdf = ticks
        plain, mesh = _contexts()
        t, _ = _streams(plain, tp, qp)
        exp = t.window_agg(
            HoppingWindow(50_000, 1_000), "count(*) as n", by="symbol"
        ).collect()
        t, _ = _streams(mesh, tp, qp)
        got = t.window_agg(
            HoppingWindow(50_000, 1_000), "count(*) as n", by="symbol"
        ).collect()
        assert mesh.last_mesh_fallback is not None
        assert "replication" in mesh.last_mesh_fallback
        keys = ["symbol", "window_start"]
        pd.testing.assert_frame_equal(
            _norm(got, keys), _norm(exp, keys), check_dtype=False
        )

    def test_session_matches_engine(self, ticks):
        tp, qp, tdf, qdf = ticks
        plain, mesh = _contexts()
        t, _ = _streams(plain, tp, qp)
        exp = t.window_agg(
            SessionWindow(50), "sum(size) as total, count(*) as n, "
            "avg(size) as mean_sz", by="symbol"
        ).collect()
        t, _ = _streams(mesh, tp, qp)
        got = t.window_agg(
            SessionWindow(50), "sum(size) as total, count(*) as n, "
            "avg(size) as mean_sz", by="symbol"
        ).collect()
        assert mesh.last_mesh_fallback is None, mesh.last_mesh_fallback
        keys = ["symbol", "session_start"]
        exp, got = _norm(exp, keys), _norm(got, keys)
        assert list(got.columns) == list(exp.columns)
        pd.testing.assert_frame_equal(got, exp, check_dtype=False)

    def test_sliding_matches_engine(self, ticks):
        from quokka_tpu.windows import SlidingWindow

        tp, qp, tdf, qdf = ticks
        plain, mesh = _contexts()
        t, _ = _streams(plain, tp, qp)
        exp = t.window_agg(
            SlidingWindow(5_000),
            "sum(size) as roll_sum, count(*) as roll_n, max(size) as roll_max",
            by="symbol",
        ).collect()
        t, _ = _streams(mesh, tp, qp)
        got = t.window_agg(
            SlidingWindow(5_000),
            "sum(size) as roll_sum, count(*) as roll_n, max(size) as roll_max",
            by="symbol",
        ).collect()
        assert mesh.last_mesh_fallback is None, mesh.last_mesh_fallback
        keys = ["symbol", "time", "size"]
        exp, got = _norm(exp, keys), _norm(got, keys)
        assert list(got.columns) == list(exp.columns)
        pd.testing.assert_frame_equal(got, exp, check_dtype=False)

    def test_sliding_zero_key_not_polluted_by_shuffle_padding(self):
        # the all_to_all zero-fills padding slots; a trailing key whose
        # limbs are genuinely all-zero (integer key 0) must not absorb them
        # — positional window bounds would silently extend over future rows
        from quokka_tpu.windows import SlidingWindow
        import pyarrow as pa

        r = np.random.default_rng(3)
        n = 64
        t = pa.table({
            "time": np.arange(n, dtype=np.int64) * 100,
            "k": np.zeros(n, dtype=np.int64),
            "v": r.integers(1, 10, n).astype(np.int64),
        })
        plain, mesh = _contexts()
        s = mesh.from_arrow_sorted(t, sorted_by="time")
        got = s.window_agg(
            SlidingWindow(5000), "sum(v) as sv", by="k"
        ).collect()
        assert mesh.last_mesh_fallback is None, mesh.last_mesh_fallback
        d = t.to_pandas()
        exp = [
            int(d.v[(d.time >= d.time[i] - 5000) & (d.time <= d.time[i])].sum())
            for i in range(n)
        ]
        got = got.sort_values("time").reset_index(drop=True)
        np.testing.assert_array_equal(
            got.sv.to_numpy().astype(np.int64), np.array(exp)
        )

    def test_byless_session_falls_back_loudly(self, ticks):
        tp, qp, tdf, qdf = ticks
        plain, mesh = _contexts()
        t, _ = _streams(plain, tp, qp)
        exp = t.window_agg(SessionWindow(7), "count(*) as n").collect()
        t, _ = _streams(mesh, tp, qp)
        got = t.window_agg(SessionWindow(7), "count(*) as n").collect()
        assert mesh.last_mesh_fallback is not None
        assert "session" in mesh.last_mesh_fallback
        keys = ["session_start"]
        pd.testing.assert_frame_equal(
            _norm(got, keys), _norm(exp, keys), check_dtype=False
        )


class TestMeshShift:
    @pytest.fixture(scope="class")
    def unique_ticks(self, tmp_path_factory):
        # unique (symbol, time) pairs: with ties the lag target is
        # order-dependent in the ENGINE too (reader order breaks ties), so
        # cross-backend equality is only defined on tie-free data
        import pyarrow as pa
        import pyarrow.parquet as pq

        r = np.random.default_rng(29)
        n, nsym = 4000, 5
        times = np.sort(r.choice(500_000, n, replace=False)).astype(np.int64)
        t = pa.table({
            "time": times,
            "symbol": np.array([f"S{i}" for i in range(nsym)])[
                r.integers(0, nsym, n)
            ],
            "size": r.integers(1, 500, n).astype(np.int64),
            "px": r.uniform(1, 100, n).round(3),
        })
        p = str(tmp_path_factory.mktemp("shift_ticks") / "t.parquet")
        pq.write_table(t, p, row_group_size=512)
        return p, t.to_pandas()

    @pytest.mark.parametrize("n", [1, 3])
    def test_shift_matches_engine_and_pandas(self, unique_ticks, n):
        tp, tdf = unique_ticks
        plain, mesh = _contexts()
        exp = (
            plain.read_sorted_parquet(tp, sorted_by="time")
            .shift(["size", "px"], n=n, by="symbol").collect()
        )
        got = (
            mesh.read_sorted_parquet(tp, sorted_by="time")
            .shift(["size", "px"], n=n, by="symbol").collect()
        )
        assert mesh.last_mesh_fallback is None, mesh.last_mesh_fallback
        keys = ["symbol", "time"]
        exp, got = _norm(exp, keys), _norm(got, keys)
        assert list(got.columns) == list(exp.columns)
        pd.testing.assert_frame_equal(got, exp, check_dtype=False)
        # independent oracle on the float column (NaN where no history)
        d = tdf.sort_values(["symbol", "time"])
        oracle = d.groupby("symbol").px.shift(n)
        oracle = oracle.reindex(d.index)
        merged = d.assign(px_oracle=oracle).sort_values(keys).reset_index(drop=True)
        np.testing.assert_allclose(
            got[f"px_shifted_{n}"].to_numpy(), merged.px_oracle.to_numpy(),
            equal_nan=True,
        )

    def test_byless_shift_falls_back_loudly(self, ticks):
        tp, qp, tdf, qdf = ticks
        plain, mesh = _contexts()
        t, _ = _streams(plain, tp, qp)
        exp = t.shift(["size"], n=1).collect()
        t, _ = _streams(mesh, tp, qp)
        got = t.shift(["size"], n=1).collect()
        assert mesh.last_mesh_fallback is not None
        assert "shift" in mesh.last_mesh_fallback
        keys = ["time", "size"]
        pd.testing.assert_frame_equal(
            _norm(got, keys), _norm(exp, keys), check_dtype=False
        )


class TestMeshCEP:
    def test_pattern_recognize_matches_engine(self):
        # CEP runs as a single-device tail over the SPMD upstream (the host
        # NFA walk has no shard_map form); results must equal the engine
        import pyarrow as pa

        r = np.random.default_rng(9)
        n = 2000
        t = pa.table({
            "time": np.arange(n, dtype=np.int64),
            "sym": np.array(["A", "B", "C"])[r.integers(0, 3, n)],
            "px": r.uniform(5, 15, n).round(2),
        })
        events = [("low", "px < 7"), ("rise", "px > low.px + 5")]
        plain, mesh = _contexts()
        s = plain.from_arrow_sorted(t, sorted_by="time")
        exp = s.pattern_recognize(events, within=50, by="sym").collect()
        s = mesh.from_arrow_sorted(t, sorted_by="time")
        got = s.pattern_recognize(events, within=50, by="sym").collect()
        assert mesh.last_mesh_fallback is None, mesh.last_mesh_fallback
        keys = ["sym", "low_time"]
        exp, got = _norm(exp, keys), _norm(got, keys)
        assert list(got.columns) == list(exp.columns)
        pd.testing.assert_frame_equal(got, exp, check_dtype=False)

    def test_empty_match_set_is_empty_not_fallback(self):
        import pyarrow as pa

        t = pa.table({
            "time": np.arange(50, dtype=np.int64),
            "sym": ["A"] * 50,
            "px": np.full(50, 10.0),
        })
        plain, mesh = _contexts()
        s = mesh.from_arrow_sorted(t, sorted_by="time")
        got = s.pattern_recognize(
            [("low", "px < 1"), ("rise", "px > low.px + 5")],
            within=10, by="sym",
        ).collect()
        # a legitimately empty match set collects as an empty frame WITHOUT
        # re-running the whole plan on the engine
        assert mesh.last_mesh_fallback is None, mesh.last_mesh_fallback
        assert len(got) == 0
        assert list(got.columns) == ["sym", "low_time", "rise_time"]


EPOCH_NS = 1_600_000_000_000_000_000  # wide int64: exercises the two-limb path


def _make_ns_ticks(tmp_path_factory):
    import pyarrow as pa
    import pyarrow.parquet as pq

    r = np.random.default_rng(17)
    n_tr, n_qt = 1500, 3000
    syms = np.array([f"N{i}" for i in range(4)])
    # span < 2^31 ns so the mesh/engine int32 window rebase stays exact
    trades = pa.table({
        "time": EPOCH_NS + np.sort(
            r.integers(0, 1_200_000_000, n_tr)
        ).astype(np.int64),
        "symbol": syms[r.integers(0, 4, n_tr)],
        "size": r.integers(1, 100, n_tr).astype(np.int64),
    })
    quotes = pa.table({
        "time": EPOCH_NS + np.sort(
            r.choice(1_200_000_000, n_qt, replace=False)
        ).astype(np.int64),
        "symbol": syms[r.integers(0, 4, n_qt)],
        "bid": r.uniform(10, 20, n_qt).round(3),
    })
    root = tmp_path_factory.mktemp("mesh_ns_ticks")
    tp, qp = str(root / "t.parquet"), str(root / "q.parquet")
    pq.write_table(trades, tp, row_group_size=512)
    pq.write_table(quotes, qp, row_group_size=512)
    return tp, qp, trades.to_pandas(), quotes.to_pandas()


@pytest.fixture(scope="module")
def ns_ticks(tmp_path_factory):
    return _make_ns_ticks(tmp_path_factory)


class TestMeshWideTimestamps:
    """ns-epoch int64 times force the wide two-limb branches: widen/not_limbs
    in mesh_asof's _side_time_limbs and the rebase_narrow path in _window."""

    @pytest.mark.parametrize("direction", ["backward", "forward"])
    def test_ns_asof_vs_pandas(self, ns_ticks, direction):
        tp, qp, tdf, qdf = ns_ticks
        plain, mesh = _contexts()
        t, q = _streams(mesh, tp, qp)
        got = t.join_asof(q, on="time", by="symbol", direction=direction).collect()
        assert mesh.last_mesh_fallback is None, mesh.last_mesh_fallback
        exp = pd.merge_asof(
            tdf.sort_values("time"), qdf.sort_values("time"),
            on="time", by="symbol", direction=direction,
        ).dropna(subset=["bid"])
        keys = ["symbol", "time", "size"]
        got, exp = _norm(got, keys), _norm(exp, keys)
        assert len(got) == len(exp)
        np.testing.assert_allclose(
            got.bid.to_numpy(), exp.bid.to_numpy(), rtol=1e-9
        )

    def test_ns_tumbling_vs_pandas(self, ns_ticks):
        tp, qp, tdf, qdf = ns_ticks
        plain, mesh = _contexts()
        size = 100_000_000  # 0.1 s in ns
        t, _ = _streams(mesh, tp, qp)
        got = t.window_agg(
            TumblingWindow(size), "sum(size) as total, count(*) as n",
            by="symbol",
        ).collect()
        assert mesh.last_mesh_fallback is None, mesh.last_mesh_fallback
        d = tdf.copy()
        d["w"] = (d.time // size) * size
        exp = (
            d.groupby(["symbol", "w"])
            .agg(total=("size", "sum"), n=("size", "size"))
            .reset_index()
        )
        got = _norm(got, ["symbol", "window_start"])
        exp = _norm(exp, ["symbol", "w"])
        assert len(got) == len(exp)
        np.testing.assert_array_equal(
            got.window_start.to_numpy(), exp.w.to_numpy()
        )
        np.testing.assert_array_equal(got.total.to_numpy(), exp.total.to_numpy())
        np.testing.assert_array_equal(got.n.to_numpy(), exp.n.to_numpy())

    def test_ns_tumbling_matches_engine(self, ns_ticks):
        tp, qp, tdf, qdf = ns_ticks
        plain, mesh = _contexts()
        size = 100_000_000
        t, _ = _streams(plain, tp, qp)
        exp = t.window_agg(
            TumblingWindow(size), "sum(size) as total", by="symbol"
        ).collect()
        t, _ = _streams(mesh, tp, qp)
        got = t.window_agg(
            TumblingWindow(size), "sum(size) as total", by="symbol"
        ).collect()
        assert mesh.last_mesh_fallback is None, mesh.last_mesh_fallback
        keys = ["symbol", "window_start"]
        pd.testing.assert_frame_equal(
            _norm(got, keys), _norm(exp, keys), check_dtype=False
        )
