"""Observability layer: flight recorder ring, timeline merger, Chrome
trace export, stall analysis, typed metrics — plus the acceptance e2e: a
deliberately wedged two-worker run produces a merged Chrome-trace JSON and
a stall report naming the stuck worker and its in-flight task, within
seconds of the wedge instead of the historical bare 600 s timeout."""

import io
import json
import os
import subprocess
import sys
import time

from quokka_tpu import obs
from quokka_tpu.obs.recorder import FlightRecorder

# -- ring buffer -------------------------------------------------------------


def test_ring_overflow_keeps_newest_events():
    rec = FlightRecorder(capacity=16, enabled=True)
    for i in range(40):
        rec.record("k", f"e{i}")
    evs = rec.snapshot()
    assert len(evs) == 16
    assert [e[0] for e in evs] == list(range(24, 40))  # newest 16, in order
    assert evs[-1][3] == "e39"


def test_ring_snapshot_since_and_last_n():
    rec = FlightRecorder(capacity=64, enabled=True)
    for i in range(10):
        rec.record("k", f"e{i}")
    assert [e[3] for e in rec.snapshot(since=6)] == ["e7", "e8", "e9"]
    assert [e[3] for e in rec.snapshot(last_n=2)] == ["e8", "e9"]


def test_ring_disabled_records_nothing():
    rec = FlightRecorder(capacity=16, enabled=False)
    assert rec.record("k", "x") == -1
    assert rec.snapshot() == []


def test_current_activity_marker():
    rec = FlightRecorder(capacity=16, enabled=True)
    with rec.activity("rpc:get"):
        cur = rec.current()
        assert any(name == "rpc:get" for name, _age in cur.values())
    assert rec.current() == {}


def test_nested_activity_restores_outer_marker():
    # a dispatch marker must survive the RPCs it performs: wedging AFTER
    # the last completed RPC still shows the task in watchdog/stall dumps
    rec = FlightRecorder(capacity=16, enabled=True)
    with rec.activity("task:exec:a2c0"):
        with rec.activity("rpc:ntt_pop"):
            assert [n for n, _ in rec.current().values()] == ["rpc:ntt_pop"]
        assert [n for n, _ in rec.current().values()] == ["task:exec:a2c0"]
    assert rec.current() == {}


def test_dump_text_renders_tail_and_activity():
    rec = FlightRecorder(capacity=16, enabled=True)
    rec.record("task", "exec:a1c0", dur=0.01)
    rec.set_current("rpc:ntt_pop")
    out = io.StringIO()
    rec.dump_text(out)
    text = out.getvalue()
    assert "exec:a1c0" in text and "rpc:ntt_pop" in text


# -- merger + chrome export --------------------------------------------------


def _ev(seq, ts, kind="k", name="n", dur=0.0, thread="t0", args=None):
    return (seq, ts, kind, name, dur, thread, args)


def test_merged_timeline_is_monotonic_across_workers():
    streams = {
        "worker-0": [_ev(0, 10.0), _ev(1, 12.0), _ev(2, 14.0)],
        "worker-1": [_ev(0, 11.0), _ev(1, 13.0)],
        "coordinator": [_ev(5, 9.5), _ev(6, 13.5)],
    }
    merged = obs.merge_streams(streams)
    assert len(merged) == 7
    ts = [d["ts"] for d in merged]
    assert ts == sorted(ts)  # one wall-clock axis, never decreasing
    # per-stream order survives the merge
    w0 = [d["seq"] for d in merged if d["pid"] == "worker-0"]
    assert w0 == sorted(w0)


def test_chrome_trace_export_shape():
    merged = obs.merge_streams({
        "worker-0": [_ev(0, 100.0, "span", "exec.Agg", dur=0.25),
                     _ev(1, 100.5, "hb", "worker-0")],
    })
    trace = obs.to_chrome_trace(merged)
    evs = trace["traceEvents"]
    assert len(evs) == 2
    span = next(e for e in evs if e["ph"] == "X")
    inst = next(e for e in evs if e["ph"] == "i")
    assert span["dur"] == 0.25 * 1e6 and span["ts"] == 0.0  # rebased start
    assert span["pid"] == "worker-0" and span["cat"] == "span"
    assert inst["name"] == "worker-0"
    json.dumps(trace)  # must be serializable as-is


def test_write_chrome_trace_roundtrip(tmp_path):
    p = str(tmp_path / "t.trace.json")
    obs.write_chrome_trace(p, obs.merge_streams(
        {"w": [_ev(0, 1.0, dur=0.1)]}))
    with open(p) as f:
        data = json.load(f)
    assert data["traceEvents"][0]["ph"] == "X"


# -- stall analysis ----------------------------------------------------------


def test_find_stuck_names_silent_worker_and_inflight_task():
    now = 1000.0
    heartbeats = {0: now - 9.0, 1: now - 0.1}
    inflight = {0: (2, 0, "exec", now - 9.2), 1: (1, 1, "input", now - 0.2)}
    stuck = obs.merge.find_stuck(heartbeats, inflight, now=now)
    assert [w for w, _, _ in stuck] == [0]
    head = obs.merge.stuck_headline(stuck)
    assert "stuck worker 0" in head
    assert "exec" in head and "actor 2" in head and "channel 0" in head


def test_stuck_headline_distinguishes_missing_heartbeat_data():
    # embedded dumps have no per-worker heartbeats: the verdict must not
    # claim "all heartbeats fresh" about data it never had
    assert "fresh" in obs.merge.stuck_headline([], have_heartbeats=True)
    head = obs.merge.stuck_headline([], have_heartbeats=False)
    assert "no per-worker heartbeat data" in head


def test_stall_report_contains_verdict_workers_and_events():
    now = 1000.0
    merged = obs.merge_streams(
        {"worker-0": [_ev(0, now - 10.0, "task", "exec:a2c0", dur=0.5)]})
    report = obs.stall_report(
        "unit-test stall", merged,
        heartbeats={0: now - 9.0, 1: now - 0.1},
        states={1: {"phase": "idle"}},
        inflight={0: (2, 0, "exec", now - 9.2)},
        ntt_depth={(2,): 3}, now=now)
    assert "reason: unit-test stall" in report
    assert "stuck worker 0" in report and "WEDGED" in report
    assert "worker 1" in report and "exec:a2c0" in report


def test_dump_flight_writes_trace_and_report(tmp_path):
    now = time.time()
    trace, report, head = obs.dump_flight(
        "unit dump", {"worker-0": [_ev(0, now, "task", "exec:a1c0", 0.1)]},
        heartbeats={0: now - 30.0}, inflight={0: (1, 0, "exec", now - 31.0)},
        directory=str(tmp_path), echo=False)
    assert os.path.exists(trace) and os.path.exists(report)
    assert "stuck worker 0" in head
    with open(trace) as f:
        assert json.load(f)["traceEvents"]
    with open(report) as f:
        text = f.read()
    assert "stuck worker 0" in text and "perfetto" in text


# -- spans feed both the summary and the recorder ----------------------------


def test_span_lands_in_summary_and_recorder(monkeypatch):
    from quokka_tpu.obs import spans

    spans.set_enabled(True)
    spans.reset()
    before = obs.RECORDER.snapshot()
    last = before[-1][0] if before else -1
    with spans.span("unit.work"):
        pass
    spans.add("unit.add", 0.25, count=2)
    st = spans.stats()
    assert st["unit.work"]["count"] == 1
    assert st["unit.add"] == {"count": 2, "total_s": 0.25}
    assert "unit.work" in spans.summary()
    if obs.RECORDER.enabled:
        names = [e[3] for e in obs.RECORDER.snapshot(since=last)
                 if e[2] == "span"]
        assert "unit.work" in names and "unit.add" in names
    spans.reset()
    spans.set_enabled(os.environ.get("QUOKKA_TRACE", "0")
                      not in ("0", "", "false"))


def test_tracing_shim_reexports_obs_spans():
    from quokka_tpu.obs import spans
    from quokka_tpu.utils import tracing

    assert tracing.span is spans.span and tracing.summary is spans.summary


# -- typed metrics -----------------------------------------------------------


def test_registry_counters_and_gauges():
    from quokka_tpu.obs.metrics import Registry

    reg = Registry()
    reg.counter("c").inc()
    reg.counter("c").inc(4)
    reg.gauge("g").set(2.5)
    assert reg.snapshot() == {"c": 5, "g": 2.5}
    reg.reset()
    assert reg.snapshot() == {}


def test_engine_metrics_snapshot_shape_matches_store_contract():
    m = obs.EngineMetrics()
    assert not m
    m.task(1, 0, 10, 256)
    m.task(1, 0, 5, 128)
    m.task(2, 1, None, 0)
    assert m and m.dirty == 3
    snap = m.snapshot()
    assert snap[(1, 0)] == {"tasks": 2, "rows": 15, "bytes": 384}
    assert snap[(2, 1)] == {"tasks": 1, "rows": 0, "bytes": 0}
    assert "real_compiles" in snap["__compile__"]
    assert m.dirty == 0


def test_engine_metrics_deferred_device_rows_resolve_at_flush():
    class FakeDeviceScalar:
        def __int__(self):
            return 7

    m = obs.EngineMetrics()
    m.task(0, 0, FakeDeviceScalar(), 0)
    assert m.snapshot()[(0, 0)]["rows"] == 7


# -- coordinator store bookkeeping -------------------------------------------


def test_heartbeat_state_and_inflight_pop_records():
    from quokka_tpu.runtime.state import WorkerState
    from quokka_tpu.runtime.store_service import CoordinatorStore
    from quokka_tpu.runtime.task import ExecutorTask

    cs = CoordinatorStore()
    st = WorkerState(worker_id=0, phase="run", task=("exec", 2, 0),
                     last_progress=123.0, queue_hint=4, events_seq=99)
    cs.heartbeat(0, st)
    cs.heartbeat(1)  # bare heartbeat still works (startup barrier path)
    assert cs.worker_states[0].task == ("exec", 2, 0)
    assert 1 in cs.heartbeats and 1 not in cs.worker_states
    cs.ntt_push(2, ExecutorTask(2, 0, 0, 0, {}))
    task = cs.ntt_pop(2, [0], 0)
    assert task is not None
    actor, ch, kind, t, args = cs.inflight[0]
    assert (actor, ch, kind) == (2, 0, "exec")
    assert "state_seq=0" in args and "out_seq=0" in args
    cs.flight_append(0, [_ev(0, 1.0), _ev(1, 2.0)])
    assert len(cs.flight_streams()["worker-0"]) == 2


def test_resolve_timeout_env_and_explicit(monkeypatch):
    from quokka_tpu.runtime.distributed import (
        DEFAULT_RUN_TIMEOUT,
        _resolve_timeout,
    )

    monkeypatch.delenv("QK_COORD_TIMEOUT", raising=False)
    assert _resolve_timeout(None) == DEFAULT_RUN_TIMEOUT
    assert _resolve_timeout(42.0) == 42.0
    monkeypatch.setenv("QK_COORD_TIMEOUT", "7")
    assert _resolve_timeout(None) == 7.0
    assert _resolve_timeout(300.0) == 300.0  # explicit beats env
    monkeypatch.setenv("QK_COORD_TIMEOUT", "junk")
    assert _resolve_timeout(None) == DEFAULT_RUN_TIMEOUT


# -- bench breakdown ---------------------------------------------------------


def test_bench_span_breakdown_buckets():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "qk_bench", os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    br = bench._span_breakdown({
        "reader.execute": {"count": 2, "total_s": 1.0},
        "bridge.to_device": {"count": 2, "total_s": 0.5},
        "emit.result_d2h": {"count": 1, "total_s": 0.25},
        "exec.AggExecutor": {"count": 3, "total_s": 2.0},
        # push/spill are TRANSFER (exchange bookkeeping + HBQ spill d2h),
        # matching the critical-path profiler's attribution
        "push.input": {"count": 2, "total_s": 0.5},
        "spill.hbq": {"count": 1, "total_s": 0.25},
        "misc.thing": {"count": 1, "total_s": 0.125},
    })
    assert br == {"read_s": 1.0, "transfer_s": 1.5, "compute_s": 2.0,
                  "other_s": 0.125}


# -- acceptance e2e: wedged two-worker run -> flight dump --------------------


def test_wedged_run_dumps_merged_trace_and_stall_report(tmp_path):
    """Reuses the deliberately-deadlocked two-worker fixture WITHOUT the
    sanitizer: the coordinator's QK_COORD_TIMEOUT fires in seconds, and the
    stall detector must leave behind (a) a merged Chrome-trace JSON and
    (b) a stall report naming the stuck worker and its in-flight task."""
    script = os.path.join(os.path.dirname(__file__),
                          "sanitize_deadlock_case.py")
    env = {k: v for k, v in os.environ.items() if k != "QK_SANITIZE"}
    env.update({
        "JAX_PLATFORMS": "cpu",
        "QK_COORD_TIMEOUT": "25",
        "QK_DUMP_DIR": str(tmp_path),
    })
    t0 = time.time()
    r = subprocess.run([sys.executable, script], capture_output=True,
                       text=True, timeout=240, env=env)
    elapsed = time.time() - t0
    out = r.stdout + r.stderr
    assert r.returncode != 0, out
    assert "UNEXPECTED-COMPLETION" not in out, out
    assert elapsed < 180, f"took {elapsed:.0f}s — stall detector never fired"
    assert "exceeded timeout" in out, out
    traces = [f for f in os.listdir(tmp_path) if f.endswith(".trace.json")]
    reports = [f for f in os.listdir(tmp_path) if f.endswith(".report.txt")]
    assert traces and reports, (os.listdir(tmp_path), out)
    with open(os.path.join(tmp_path, traces[0])) as f:
        trace = json.load(f)
    pids = {e["pid"] for e in trace["traceEvents"]}
    assert any(p.startswith("worker-") for p in pids), pids
    with open(os.path.join(tmp_path, reports[0])) as f:
        report = f.read()
    # the verdict names the stuck worker and its in-flight exec task
    assert "stuck worker" in report, report
    assert "in-flight exec task" in report, report
    assert "WEDGED" in report, report
    # ... and the raised error carries the same verdict + the report path
    assert "stuck worker" in out, out
