"""Tier-1 lint gate: the shipped tree is clean against the checked-in
baseline, and the baseline itself is healthy (no stale entries, every entry
carries a real rationale).  This is the gate every later PR runs under —
new invariant violations fail here; the baseline may only shrink."""

import json
import os

from quokka_tpu.analysis.lint import (
    DEFAULT_BASELINE,
    load_baseline,
    run_lint,
)

PKG = os.path.dirname(os.path.dirname(os.path.abspath(DEFAULT_BASELINE)))
assert os.path.basename(PKG) == "quokka_tpu", PKG


def test_package_is_clean_against_baseline():
    findings = run_lint([PKG])
    baseline = load_baseline(DEFAULT_BASELINE)
    new = [f for f in findings if f.key() not in baseline]
    assert not new, "new lint findings (fix or baseline with rationale):\n" \
        + "\n".join(f.render() for f in new)


def test_baseline_has_no_stale_entries():
    """A fixed finding must leave the baseline in the same PR (the file may
    only shrink; stale keys would hide a regression re-introducing the
    same code shape elsewhere in the diff noise)."""
    current = {f.key() for f in run_lint([PKG])}
    stale = sorted(k for k in load_baseline(DEFAULT_BASELINE)
                   if k not in current)
    assert not stale, "stale baseline entries (run --write-baseline):\n" \
        + "\n".join(stale)


def test_baseline_entries_carry_rationales():
    with open(DEFAULT_BASELINE) as f:
        entries = json.load(f)["findings"]
    bad = [k for k, v in entries.items()
           if not isinstance(v, str) or len(v.strip()) < 10 or "TODO" in v]
    assert not bad, f"baseline entries without a real rationale: {bad}"
