"""Tier-1 static-analysis gate: the shipped tree is clean against the
checked-in lint baseline, the baseline itself is healthy (no stale entries,
every entry carries a real rationale), the control-store protocol verifier
(QK014-QK017) is clean with NO baseline, and the whole lint pass fits the
wall-time budget.  This is the gate every later PR runs under — new
invariant violations fail here; the lint baseline may only shrink."""

import json
import os
import time

from quokka_tpu.analysis.lint import (
    DEFAULT_BASELINE,
    load_baseline,
    run_lint,
)
from quokka_tpu.analysis.protocol import verify as protocol_verify

PKG = os.path.dirname(os.path.dirname(os.path.abspath(DEFAULT_BASELINE)))
assert os.path.basename(PKG) == "quokka_tpu", PKG

# Full-package lint wall-time budget.  The lint pass runs in every tier-1
# invocation and in `make verify-static`; an interprocedural rule that
# regresses to quadratic blows this long before it blows CI.
LINT_BUDGET_S = 20.0


def test_package_is_clean_against_baseline_within_budget():
    t0 = time.monotonic()
    findings = run_lint([PKG])
    elapsed = time.monotonic() - t0
    baseline = load_baseline(DEFAULT_BASELINE)
    new = [f for f in findings if f.key() not in baseline]
    assert not new, "new lint findings (fix or baseline with rationale):\n" \
        + "\n".join(f.render() for f in new)
    assert elapsed < LINT_BUDGET_S, (
        f"lint pass took {elapsed:.1f}s (budget {LINT_BUDGET_S}s) — an "
        "interprocedural rule has regressed")


def test_protocol_verifier_is_clean():
    """QK014-QK017 run with NO baseline: a dead store write, an un-GC'd
    growth class, a lock-order cycle, or a torn checkpoint commit fails
    tier-1 outright — fix the code, don't suppress."""
    findings, ops = protocol_verify([PKG])
    assert not findings, "protocol violations (no baseline for these):\n" \
        + "\n".join(f.render() for f in findings)
    assert len(ops) > 100, "protocol verifier lost its site inventory"


def test_baseline_has_no_stale_entries():
    """A fixed finding must leave the baseline in the same PR (the file may
    only shrink; stale keys would hide a regression re-introducing the
    same code shape elsewhere in the diff noise)."""
    current = {f.key() for f in run_lint([PKG])}
    stale = sorted(k for k in load_baseline(DEFAULT_BASELINE)
                   if k not in current)
    assert not stale, "stale baseline entries (run --write-baseline):\n" \
        + "\n".join(stale)


def test_baseline_entries_carry_rationales():
    with open(DEFAULT_BASELINE) as f:
        entries = json.load(f)["findings"]
    bad = [k for k, v in entries.items()
           if not isinstance(v, str) or len(v.strip()) < 10 or "TODO" in v]
    assert not bad, f"baseline entries without a real rationale: {bad}"


def test_plan_verifier_corpus_is_clean():
    """QK021-QK024 run with NO baseline over every plannable query shape
    the tests and bench exercise (same corpus as `python -m
    quokka_tpu.analysis.planck`): a schema-propagation break, an uncovered
    exchange key, an illegal fusion, or unsafe order metadata fails tier-1
    outright."""
    from quokka_tpu.analysis import planck

    failures = planck.check_corpus()
    assert not failures, "plan invariant violations (no baseline):\n" \
        + "\n".join(f"{name}: {err}" for name, err in failures)
    assert len(planck.corpus()) >= 12, "planck lost its query corpus"


def test_plan_fuzz_batch_is_clean():
    """A small deterministic slice of the differential plan fuzzer runs in
    tier-1 (the full 200-seed sweep is `make plan-fuzz`): each seed's plan
    under every pass prefix and QK_STAGE_FUSE=0 must verify statically and
    execute bit-identically to the unoptimized plan."""
    from quokka_tpu.analysis import planfuzz

    dirty = [planfuzz.run_seed(s, shrink=False)
             for s in range(40)]
    dirty = [r for r in dirty if not r.ok]
    assert not dirty, "differential fuzz failures:\n" \
        + "\n".join(r.summary() for r in dirty)
