"""Device-resident shuffle data plane (ISSUE 6): the multi-partition split
kernel (masked views vs one-kernel compacted), the sync-free push-path
contract, the async HBQ spill's flush barriers, and the spill/replay round
trip staying bit-exact under injected spill corruption."""

import os

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from quokka_tpu import QuokkaContext, config, obs
from quokka_tpu.chaos import CHAOS
from quokka_tpu.dataset.readers import InputArrowDataset
from quokka_tpu.ops import bridge, kernels
from quokka_tpu.ops.batch import DeviceBatch


@pytest.fixture(autouse=True)
def _chaos_off():
    CHAOS.disable()
    yield
    CHAOS.disable()


def _batch(n=5000, seed=0, invalid_frac=0.3, n_keys=64):
    r = np.random.default_rng(seed)
    table = pa.table({
        "k": r.integers(0, n_keys, n).astype(np.int64),
        "v": r.normal(size=n),
        "s": pa.array(np.array([f"s{i % 7}" for i in range(n)])),
    })
    b = bridge.arrow_to_device(table)
    if invalid_frac:
        import jax.numpy as jnp

        mask = jnp.asarray(r.random(b.padded_len) >= invalid_frac)
        b = kernels.apply_mask(b, mask)
    return b


def _rows(part: DeviceBatch) -> pd.DataFrame:
    """Valid rows of a partition, in stored order."""
    return bridge.to_pandas(part).reset_index(drop=True)


class TestMultiPartitionKernel:
    @pytest.mark.parametrize("n_parts", [2, 3, 4])
    def test_masked_vs_compacted_equivalence(self, n_parts):
        """The two split modes must deliver identical rows per partition,
        in identical (source) order — the fault-tolerance tape replay
        depends on partition contents being mode-independent."""
        b = _batch(seed=1)
        pids = kernels.partition_ids(b, ["k"], n_parts)
        masked = kernels.split_by_partition(b, pids, n_parts, compact=False)
        compacted = kernels.split_by_partition(b, pids, n_parts, compact=True)
        assert len(masked) == len(compacted) == n_parts
        total = 0
        for m, c in zip(masked, compacted):
            dm, dc = _rows(m), _rows(c)
            pd.testing.assert_frame_equal(dm, dc)
            total += len(dm)
        assert total == b.count_valid()

    def test_masked_parts_share_parent_buffers(self):
        b = _batch(seed=2)
        pids = kernels.partition_ids(b, ["k"], 2)
        parts = kernels.split_by_partition(b, pids, 2, compact=False)
        for p in parts:
            assert p.columns["v"].data is b.columns["v"].data
            assert p.padded_len == b.padded_len

    def test_empty_partitions(self):
        """Keys concentrated on one partition: the others are empty but
        well-formed (every consumer receives a batch for its channel)."""
        n = 3000
        table = pa.table({"k": np.zeros(n, dtype=np.int64),
                          "v": np.arange(n, dtype=np.float64)})
        b = bridge.arrow_to_device(table)
        pids = kernels.partition_ids(b, ["k"], 4)
        for compact in (False, True):
            parts = kernels.split_by_partition(b, pids, 4, compact=compact)
            counts = [p.count_valid() for p in parts]
            assert sorted(counts)[:3] == [0, 0, 0]
            assert sum(counts) == n

    def test_all_invalid_batch(self):
        b = _batch(seed=3, invalid_frac=1.0)
        assert b.count_valid() == 0
        pids = kernels.partition_ids(b, ["k"], 3)
        for compact in (False, True):
            parts = kernels.split_by_partition(b, pids, 3, compact=compact)
            assert [p.count_valid() for p in parts] == [0, 0, 0]
            for p in parts:
                assert len(_rows(p)) == 0

    def test_n_parts_1_fast_path(self):
        """Fan-in of one: the batch passes through untouched — no mask, no
        gather, no sync."""
        b = _batch(seed=4)
        pids = kernels.partition_ids(b, ["k"], 1)
        for compact in (False, True):
            parts = kernels.split_by_partition(b, pids, 1, compact=compact)
            assert len(parts) == 1 and parts[0] is b

    def test_compacted_uniform_buckets(self):
        """Balanced hash splits compact to ONE bucket size across all
        partitions (the downstream shape-space collapse)."""
        b = _batch(seed=5, invalid_frac=0.0, n_keys=1024)
        pids = kernels.partition_ids(b, ["k"], 4)
        parts = kernels.split_by_partition(b, pids, 4, compact=True)
        assert len({p.padded_len for p in parts}) == 1

    def test_masked_split_zero_host_syncs(self):
        """The push-path contract the shuffle-smoke gate enforces: a masked
        split never increments the blocking-readback counter."""
        b = _batch(seed=6)
        pids = kernels.partition_ids(b, ["k"], 4)
        before = obs.REGISTRY.counter("shuffle.host_syncs").value
        kernels.split_by_partition(b, pids, 4, compact=False)
        assert obs.REGISTRY.counter("shuffle.host_syncs").value == before

    def test_masked_counts_noted_async(self):
        b = _batch(seed=7)
        pids = kernels.partition_ids(b, ["k"], 2)
        parts = kernels.split_by_partition(b, pids, 2, compact=False)
        for p in parts:
            assert p.nrows is None and p.nrows_dev is not None

    def test_order_preserved_within_partition(self):
        """Both modes keep source row order inside each partition (ordered
        asof/window streams shuffle through the same kernels)."""
        n = 4000
        table = pa.table({"k": (np.arange(n) % 3).astype(np.int64),
                          "t": np.arange(n, dtype=np.int64)})
        b = bridge.arrow_to_device(table)
        pids = kernels.partition_ids(b, ["k"], 3)
        for compact in (False, True):
            for p in kernels.split_by_partition(b, pids, 3, compact=compact):
                t = _rows(p)["t"].to_numpy()
                assert (np.diff(t) > 0).all()


class TestAsyncSpill:
    def test_spill_submit_and_flush_barrier(self, tmp_path):
        """_spill_submit runs off-thread; _flush_spills makes the artifact
        durable (the barrier checkpoint/recovery rely on)."""
        from quokka_tpu.runtime.engine import Engine
        from quokka_tpu.runtime.hbq import HBQ

        class _G:
            pass

        eng = Engine.__new__(Engine)
        eng.g = _G()
        eng.g.hbq = HBQ(str(tmp_path))
        b = bridge.arrow_to_device(pa.table({"a": [1, 2, 3]}))
        name = (0, 0, 0, 1, 0, 0)
        try:
            eng._spill_submit(name, b)
            eng._flush_spills()
            got = eng.g.hbq.get(name)
            assert got is not None and got.column("a").to_pylist() == [1, 2, 3]
        finally:
            eng._shutdown_spill()

    def test_spill_error_surfaces_at_flush(self, tmp_path):
        """A failing spill write must fail the query loudly at the next
        barrier, never vanish into the background pool."""
        from quokka_tpu.runtime.engine import Engine

        class _BadHBQ:
            def put(self, name, table):
                raise OSError("disk on fire")

        class _G:
            pass

        eng = Engine.__new__(Engine)
        eng.g = _G()
        eng.g.hbq = _BadHBQ()
        b = bridge.arrow_to_device(pa.table({"a": [1]}))
        try:
            eng._spill_submit((0, 0, 0, 1, 0, 0), b)
            with pytest.raises(OSError, match="disk on fire"):
                eng._flush_spills()
        finally:
            eng._spill_pool = None  # already drained; avoid double shutdown


def _join_query(fact, dim, **cfg):
    # optimize=False pins the plan shape, so inject_failure channel ids are
    # stable (same discipline as the fault-tolerance tests)
    ctx = QuokkaContext(optimize=False)
    for k, v in cfg.items():
        ctx.set_config(k, v)
    f = ctx.read_dataset(InputArrowDataset(fact, batch_rows=512))
    d = ctx.read_dataset(InputArrowDataset(dim, batch_rows=512))
    return (
        f.join(d, left_on="k", right_on="pk")
        .groupby("g").agg_sql("sum(v) as sv, count(*) as n")
        .collect().sort_values("g").reset_index(drop=True)
    )


class TestSpillReplayRoundTrip:
    def test_shuffle_spill_replay_bit_exact_under_corrupt_spill(
            self, tmp_path):
        """Q3-shaped join+aggregate through the new split kernels with EVERY
        spill write corrupted and a mid-run channel loss: the round trip
        (async spill -> quarantine -> replay/regenerate) must stay
        bit-exact, and the detection counter must move."""
        r = np.random.default_rng(11)
        n = 6000
        fact = pa.table({"k": r.integers(0, 50, n).astype(np.int64),
                         "v": r.integers(0, 100, n).astype(np.float64)})
        dim = pa.table({"pk": np.arange(50, dtype=np.int64),
                        "g": (np.arange(50) % 5).astype(np.int64)})
        baseline = _join_query(fact, dim)
        before = obs.REGISTRY.counter("integrity.corrupt").value
        CHAOS.configure("seed=77,corrupt_spill=1.0")
        try:
            got = _join_query(
                fact, dim,
                fault_tolerance=True, hbq_path=str(tmp_path),
                inject_failure={"after_tasks": 14,
                                "channels": [(2, 0)]},  # join (optimize=False)
            )
        finally:
            CHAOS.disable()
        pd.testing.assert_frame_equal(got, baseline, check_exact=True,
                                      check_dtype=False)
        assert obs.REGISTRY.counter("integrity.corrupt").value > before

    def test_sync_spill_env_fallback(self, tmp_path, monkeypatch):
        """QK_SPILL_ASYNC=0 restores the synchronous spill (debug escape
        hatch): identical results, spill landed by push return."""
        monkeypatch.setattr(config, "SPILL_ASYNC", False)
        r = np.random.default_rng(12)
        fact = pa.table({"k": r.integers(0, 20, 2000).astype(np.int64),
                         "v": r.integers(0, 9, 2000).astype(np.float64)})
        dim = pa.table({"pk": np.arange(20, dtype=np.int64),
                        "g": (np.arange(20) % 3).astype(np.int64)})
        baseline = _join_query(fact, dim)
        got = _join_query(fact, dim,
                          fault_tolerance=True, hbq_path=str(tmp_path))
        pd.testing.assert_frame_equal(got, baseline, check_exact=True,
                                      check_dtype=False)
