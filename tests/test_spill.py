"""Spill tier (VERDICT r1 item 5): external merge sort, grace
(disk-partitioned) join, and byte-based cache backpressure.  Thresholds are
monkeypatched low so tiny datasets exercise the disk paths; results must be
identical to the in-memory paths / pandas."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from quokka_tpu import QuokkaContext, config
from quokka_tpu.ops import bridge


@pytest.fixture
def spill_small(monkeypatch):
    monkeypatch.setattr(config, "SPILL_SORT_ROWS", 4000)
    monkeypatch.setattr(config, "SPILL_MERGE_CHUNK_ROWS", 1500)
    monkeypatch.setattr(config, "SPILL_JOIN_BUILD_ROWS", 3000)
    monkeypatch.setattr(config, "SPILL_JOIN_FANOUT", 4)


def test_external_sort_query(spill_small):
    r = np.random.default_rng(1)
    n = 30000
    t = pa.table({
        "x": r.integers(-10**12, 10**12, n),
        "s": np.array(["p", "q", "r"])[r.integers(0, 3, n)],
        "v": r.uniform(0, 1, n).round(6),
    })
    from quokka_tpu import logical
    from quokka_tpu.dataset.readers import InputArrowDataset

    ctx = QuokkaContext()
    # small reader batches -> the sort accumulates past the spill threshold
    # repeatedly and must merge MANY sorted runs
    src = ctx.new_stream(
        logical.SourceNode(InputArrowDataset(t, batch_rows=3000), list(t.column_names))
    )
    got = src.sort(["s", "x"], descending=[False, True]).collect()
    exp = t.to_pandas().sort_values(["s", "x"], ascending=[True, False]).reset_index(drop=True)
    np.testing.assert_array_equal(got.x.to_numpy(), exp.x.to_numpy())
    assert got.s.tolist() == exp.s.tolist()
    np.testing.assert_allclose(got.v.to_numpy(), exp.v.to_numpy())


def test_grace_join_query(spill_small):
    r = np.random.default_rng(2)
    n_build, n_probe = 12000, 25000
    build = pa.table({
        "k": r.permutation(n_build).astype(np.int64),
        "name": np.array([f"n{i % 17}" for i in range(n_build)]),
        "w": r.uniform(0, 5, n_build).round(4),
    })
    probe = pa.table({
        "k": r.integers(0, n_build * 2, n_probe).astype(np.int64),  # ~half miss
        "v": r.uniform(0, 9, n_probe).round(4),
    })
    ctx = QuokkaContext()
    for how in ("inner", "left", "semi", "anti"):
        got = (
            ctx.from_arrow(probe)
            .join(ctx.from_arrow(build), on="k", how=how)
            .collect()
        )
        pdf, bdf = probe.to_pandas(), build.to_pandas()
        if how in ("semi", "anti"):
            hit = pdf.k.isin(bdf.k)
            exp = pdf[hit] if how == "semi" else pdf[~hit]
            assert len(got) == len(exp), how
            np.testing.assert_allclose(
                np.sort(got.v.to_numpy()), np.sort(exp.v.to_numpy()), err_msg=how
            )
        else:
            exp = pdf.merge(bdf, on="k", how=how)
            assert len(got) == len(exp), how
            np.testing.assert_allclose(got.v.sum(), exp.v.sum(), rtol=1e-9)
            if how == "left":
                assert got.name.isna().sum() == exp.name.isna().sum()
            np.testing.assert_allclose(
                got.w.sum(), exp.w.sum(), rtol=1e-9, err_msg=how
            )


def test_grace_join_then_agg(spill_small):
    r = np.random.default_rng(3)
    build = pa.table({
        "k": np.arange(8000, dtype=np.int64),
        "grp": np.array(["A", "B", "C", "D"])[np.arange(8000) % 4],
    })
    probe = pa.table({
        "k": r.integers(0, 8000, 20000).astype(np.int64),
        "v": r.uniform(0, 2, 20000).round(5),
    })
    ctx = QuokkaContext()
    got = (
        ctx.from_arrow(probe)
        .join(ctx.from_arrow(build), on="k")
        .groupby("grp")
        .agg_sql("sum(v) as sv, count(*) as n")
        .collect()
        .sort_values("grp")
        .reset_index(drop=True)
    )
    df = probe.to_pandas().merge(build.to_pandas(), on="k")
    exp = df.groupby("grp").v.agg(["sum", "size"]).reset_index()
    np.testing.assert_allclose(got.sv.to_numpy(), exp["sum"].to_numpy(), rtol=1e-9)
    assert got.n.tolist() == exp["size"].tolist()


def test_byte_backpressure():
    from quokka_tpu.runtime.cache import BatchCache

    t = pa.table({"v": np.arange(5000, dtype=np.int64)})
    b = bridge.arrow_to_device(t)
    cache = BatchCache(mem_limit_bytes=1)
    assert cache.puttable()
    cache.put((0, 0, 0, 1, 0, 0), b)
    assert not cache.puttable()  # bytes, not batch count, gate ingestion
    cache.gc([(0, 0, 0, 1, 0, 0)])
    assert cache.puttable()


def test_parallel_range_sort_with_spill(spill_small, tmp_path):
    """Review regression: a range-partitioned parallel sort whose channels
    each spill (multi-seq output) must still concat in channel order —
    (seq, channel)-interleaved delivery would shuffle the ranges."""
    import pyarrow.parquet as pq

    r = np.random.default_rng(9)
    n = 40000
    t = pa.table({"x": r.permutation(n).astype(np.int64),
                  "v": r.uniform(0, 1, n)})
    p = str(tmp_path / "t.parquet")
    pq.write_table(t, p, row_group_size=4096)  # sampleable, multi-batch
    ctx = QuokkaContext(exec_channels=2)
    got = ctx.read_parquet(p).sort("x").collect()
    assert (np.diff(got.x.to_numpy()) >= 0).all()
    assert len(got) == n
    got_desc = ctx.read_parquet(p).sort("x", descending=[True]).collect()
    assert (np.diff(got_desc.x.to_numpy()) <= 0).all()


def test_grace_left_join_probe_only_partitions(spill_small):
    # build keys hash into FEW partitions (all equal mod small set) while
    # probes cover every partition: probe-only partitions must emit typed
    # null payloads, not the degraded float-NaN path
    r = np.random.default_rng(11)
    build = pa.table({
        "k": (np.arange(5000, dtype=np.int64) * 4),  # clusters of hash cells
        "name": np.array([f"s{i % 5}" for i in range(5000)]),
    })
    probe = pa.table({
        "k": r.integers(0, 20000, 15000).astype(np.int64),
        "v": r.uniform(0, 1, 15000).round(5),
    })
    ctx = QuokkaContext()
    got = (
        ctx.from_arrow(probe)
        .join(ctx.from_arrow(build), on="k", how="left")
        .collect()
    )
    exp = probe.to_pandas().merge(build.to_pandas(), on="k", how="left")
    assert len(got) == len(exp)
    assert got.name.isna().sum() == exp.name.isna().sum()
    matched = got[~got.name.isna()]
    assert set(matched.name) <= {f"s{i}" for i in range(5)}
