"""Seeded QK006: a swallowed exception in a runtime-style loop."""


def drain(queue):
    while True:
        try:
            item = queue.get_nowait()
        except Exception:
            pass  # violation: the loop wedges silently on real failures
        else:
            yield item
