"""QK018 fixture: eager device allocations outside the ledgered helpers.

Three findings: a jnp array constructor, a jax.device_put, and a
jnp.asarray — all on eager (non-traced) paths, so each creates device
residency the memory ledger never sees.  The jit-wrapped kernel is exempt:
inside a trace these are lazy tracer ops, not allocations.
"""


def make_padding(jnp, n):
    return jnp.zeros((n,))  # finding 1: eager constructor, unledgered


def stage_batch(jax, arr, device):
    return jax.device_put(arr, device)  # finding 2: raw transfer


def from_host(jnp, values):
    return jnp.asarray(values)  # finding 3: eager host->device copy


def traced_pad(jax, jnp, n):
    def kernel(x):
        return x + jnp.zeros((n,))  # exempt: traces under jit below

    return jax.jit(kernel)
