"""Seeded QK025 fixture: blocking I/O while holding an obs ``*_lock``.

Three violations — a direct ``open`` under a class lock, a ``time.sleep``
under a module lock, and a helper call under a lock whose body opens a
file — plus the clean shapes the rule must NOT flag: I/O after release,
a pure helper under the lock, and a nested def (deferred execution).
"""

import threading
import time

_flush_lock = threading.Lock()


def _persist(payload, path):
    with open(path, "w") as f:
        f.write(repr(payload))


def _format(payload):
    return repr(payload)


class Ring:
    def __init__(self):
        self._lock = threading.Lock()
        self._samples = []

    def record_bad_direct(self, sample, path):
        with self._lock:
            self._samples.append(sample)
            with open(path, "a") as f:  # QK025: file I/O under the lock
                f.write(repr(sample))

    def record_bad_indirect(self, sample, path):
        with self._lock:
            self._samples.append(sample)
            _persist(sample, path)  # QK025: helper reaches open()

    def record_ok(self, sample, path):
        with self._lock:
            self._samples.append(sample)
            snap = list(self._samples)
        _persist(snap, path)  # I/O after release: the correct shape

    def format_ok(self, sample):
        with self._lock:
            return _format(sample)  # pure helper under the lock: fine


def throttle_bad():
    with _flush_lock:
        time.sleep(0.01)  # QK025: sleep while holding the lock


def throttle_ok():
    time.sleep(0.01)
    with _flush_lock:
        return None


def deferred_ok():
    with _flush_lock:
        def later(path):
            with open(path) as f:  # runs after release: not flagged
                return f.read()

        return later
