"""QK013 fixture: platform probes / platform-string gates outside the
strategy matrix.

Three findings: a direct jax.default_backend() probe, a .platform attribute
compared against a platform literal, and a config._platform() probe.
Per-backend kernel decisions must route through quokka_tpu.ops.strategy.
"""


def pick_kernel(jax, config, device):
    if jax.default_backend() == "tpu":  # finding 1: direct backend probe
        return "sort"
    if device.platform == "cpu":  # finding 2: platform-string gate
        return "hashtable"
    config._platform()  # finding 3: probe via the config helper
    return "sort"
