"""QK019 fixture: ad-hoc per-operator row/byte tallies.

Three findings: a stat-named attribute increment, a string-keyed dict
tally increment, and the ``.get()`` read-modify-write spelling.  The
operational-state names below them (``pending_rows``, ``_build_rows``)
are buffers a channel drains, not statistics — exempt by design.
"""


class JoinChannel:
    def __init__(self):
        self.rows_in = 0
        self._tally = {}
        self.pending_rows = 0
        self._build_rows = 0

    def absorb(self, batch, nb):
        self.rows_in += batch.nrows  # finding 1: attribute tally
        self._tally["bytes_out"] += nb  # finding 2: dict-slot tally

    def absorb_rmw(self, t, n):
        t["rows_in"] = t.get("rows_in", 0) + n  # finding 3: RMW spelling

    def buffer(self, table):
        self.pending_rows += table.num_rows  # exempt: operational state
        self._build_rows += table.num_rows  # exempt: build buffer
