# Seeded lint violations, one file per rule (tests/test_lint_rules.py).
# These files are PARSED by the analyzer, never imported/executed.
