"""Seeded QK001: a jit object built at module scope."""

import functools

import jax


def _double(x):
    return x * 2


# the violation: a module-level pjit object shared across engine threads
_double_jit = jax.jit(_double)

# the partial form must be caught too
_double_partial = functools.partial(jax.jit, static_argnames=())(_double)


@jax.jit
def _decorated(x):
    return x + 1
