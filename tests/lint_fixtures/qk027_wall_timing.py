"""QK027 fixture: three hand-rolled wall-clock deltas (dotted perf_counter,
time.time name pair, bare imported perf_counter).  Deadline arithmetic must
NOT fire."""

import time
from time import perf_counter


def work():
    return sum(range(10))


def dotted_delta():
    t0 = time.perf_counter()
    work()
    dt = time.perf_counter() - t0  # QK027
    return dt


def name_pair():
    a = time.time()
    work()
    b = time.time()
    return b - a  # QK027


def bare_imported():
    s = perf_counter()
    work()
    return perf_counter() - s  # QK027


def deadline_ok():
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        work()
    return deadline - time.monotonic()  # monotonic deadline: not flagged
