"""QK016 fixture: two sanitize-instrumented lock classes whose under-lock
calls form a held->acquired cycle — the two-lock deadlock precursor the
runtime recorder reports dynamically."""

import threading

from quokka_tpu.analysis import sanitize


class AlphaPlane:
    def __init__(self, beta):
        self._lock = sanitize.maybe_instrument("alpha", threading.Lock())
        self.beta = beta

    def alpha_op(self):
        # holds alpha while acquiring beta
        with self._lock:
            return self.beta.beta_op()


class BetaPlane:
    def __init__(self, alpha):
        self._lock = sanitize.maybe_instrument("beta", threading.Lock())
        self.alpha = alpha

    def beta_op(self):
        with self._lock:
            return 1

    def beta_cross(self):
        # holds beta while acquiring alpha: closes the cycle
        with self._lock:
            return self.alpha.alpha_op()
