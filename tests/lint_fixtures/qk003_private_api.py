"""Seeded QK003: private JAX API outside the compat shim."""

import jax


def in_trace() -> bool:
    # the violation: private surface used directly instead of
    # quokka_tpu.analysis.compat
    return not jax.core.trace_state_clean()
