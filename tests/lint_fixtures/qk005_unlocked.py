"""Seeded QK005: shared state mutated without the owning lock."""

import threading


class SharedTable:
    def __init__(self):
        self._lock = threading.Lock()
        self.rows = {}
        self.pending = []

    def put_locked(self, k, v):
        with self._lock:
            self.rows[k] = v  # correct: not flagged

    def put_racy(self, k, v):
        self.rows[k] = v  # violation: no lock held

    def enqueue_racy(self, task):
        self.pending.append(task)  # violation: no lock held
