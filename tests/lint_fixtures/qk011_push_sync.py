"""QK011 fixture: blocking host readbacks on the shuffle push path.

Three findings: np.asarray in push, .item() in _range_split (reached via
push -> _partition_fn -> closure), device_get in a helper reachable from
split_by_partition.
"""

import numpy as np
import jax


def push(batch, parts):
    sizes = np.asarray(batch.counts)  # finding 1: blocking readback in push
    fn = _partition_fn()
    return fn(batch, sizes), split_by_partition(batch, parts)


def _partition_fn():
    def fn(batch, sizes):
        return _range_split(batch, sizes)

    return fn


def _range_split(batch, sizes):
    return sizes.sum().item()  # finding 2: scalar readback on the push path


def split_by_partition(batch, parts):
    return _materialize(batch, parts)


def _materialize(batch, parts):
    return jax.device_get(batch.columns)  # finding 3: reachable from split
