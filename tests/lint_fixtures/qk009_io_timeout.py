"""QK009 fixture: network/socket/fsspec IO without an explicit timeout."""

import socket

import fsspec


def connect(addr):
    s = socket.create_connection(addr)  # QK009: no timeout
    s.settimeout(None)  # QK009: explicitly unbounded
    return s


def connect_none(addr):
    return socket.create_connection(addr, timeout=None)  # QK009: None = unbounded


def connect_bounded(addr):
    s = socket.create_connection(addr, timeout=5.0)  # ok: explicit timeout
    s.settimeout(10.0)  # ok: finite
    return s


def read_remote(url):
    with fsspec.open(url, "rb") as f:  # QK009: fsspec call, no timeout
        return f.read()


def move_remote(fs, src, dst):
    fs.mv(src, dst)  # QK009: bound-filesystem call, no timeout
