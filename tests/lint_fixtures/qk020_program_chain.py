"""QK020 fixture: per-batch chains of single-expression program dispatches.

Three findings: a loop-borne ``evaluate_to_column`` (one program launch per
expression per batch) and the third and fourth straight-line dispatches in
one body (beyond the two-per-batch allowance).  The two-dispatch body below
them — one predicate, one projection — is within the allowance and exempt.
"""

from quokka_tpu.ops.expr_compile import evaluate_predicate, evaluate_to_column


class ChainedExecutor:
    def __init__(self, exprs, preds):
        self.exprs = exprs
        self.preds = preds

    def execute(self, batch):
        b = batch
        for name, e in self.exprs:
            b = b.with_column(name, evaluate_to_column(e, b))  # finding 1
        return b

    def probe(self, batch, p1, p2, e1, e2):
        m = evaluate_predicate(p1, batch)
        m = m & evaluate_predicate(p2, batch)
        b = batch.with_column("a", evaluate_to_column(e1, batch))  # finding 2
        return b.with_column("z", evaluate_to_column(e2, b))  # finding 3

    def guarded(self, batch, pred, expr):
        m = evaluate_predicate(pred, batch)  # exempt: one predicate...
        b = batch.with_column("y", evaluate_to_column(expr, batch))
        return b, m  # ...plus one projection is within the allowance
