"""QK008 fixture: process-global config mutation reachable from query
execution — each of the three mutation families fires once, through one
interprocedural hop from the execution surface (a task handler, the push
path, a jit entry).  Mutations OUTSIDE that surface (module-scope import
setup, process bootstrap with no inbound call edge) are pre-query and must
NOT fire."""

import os

import jax

# NOT flagged: import-time setup runs once, before any query exists
os.environ.setdefault("QUOKKA_FIXTURE_SETUP", "1")


def mutate_backend_config(flag):
    # QK008: jax.config is process-global; flipping x64 mid-query changes
    # every concurrent query's dtype regime
    jax.config.update("jax_enable_x64", flag)


def mutate_environment(value):
    # QK008: env vars feed config.use_hash_tables()/use_host_asof() lazily
    os.environ["QUOKKA_HASH_TABLES"] = value


def mutate_config_module_global(config, rows):
    # QK008: quokka_tpu.config module globals (spill thresholds, buckets)
    config.SPILL_SORT_ROWS = rows


def handle_exec_task(task, config):
    # the task-dispatch surface: everything it reaches runs mid-query
    mutate_backend_config(True)
    mutate_environment("0")
    mutate_config_module_global(config, 1 << 20)


def fixture_main():
    # NOT flagged: process bootstrap — nothing on the execution surface
    # calls it, so its mutation has no concurrent neighbor to corrupt
    jax.config.update("jax_platforms", "cpu")
