"""QK008 fixture: process-global config mutation reachable from query
execution — each of the three mutation families fires once."""

import os

import jax


def mutate_backend_config(flag):
    # QK008: jax.config is process-global; flipping x64 mid-query changes
    # every concurrent query's dtype regime
    jax.config.update("jax_enable_x64", flag)


def mutate_environment(value):
    # QK008: env vars feed config.use_hash_tables()/use_host_asof() lazily
    os.environ["QUOKKA_HASH_TABLES"] = value


def mutate_config_module_global(config, rows):
    # QK008: quokka_tpu.config module globals (spill thresholds, buckets)
    config.SPILL_SORT_ROWS = rows
