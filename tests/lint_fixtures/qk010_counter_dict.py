"""QK010 fixture: ad-hoc counter dicts in runtime code (3 findings).

Counters belong in the typed registry (quokka_tpu.obs.REGISTRY) so the
Prometheus exporter, bench snapshots and stall reports all see them.
"""


class Cache:
    def __init__(self):
        self._stats = {"hits": 0, "misses": 0}

    def get(self, key, table):
        if key in table:
            self._stats["hits"] += 1  # QK010: += on a counter-named dict
            return table[key]
        self._stats["misses"] += 1  # QK010
        return None


def account(metrics, kind):
    # QK010: read-modify-write counter via .get
    metrics[kind] = metrics.get(kind, 0) + 1


def fine(log, sizes, k):
    log[k] = log.get(k, 0) + 1  # receiver is not counter-named: not flagged
    sizes[k] = 7  # plain store, not an increment: not flagged
