"""Caller side: every import form the resolver handles — relative module
binding, from-import alias, absolute import alias — plus a class call,
a callback reference, and static/tainted call sites for ``sized``."""

import quokka_tpu.flowfix.alpha as qalpha

from . import alpha
from .alpha import helper as hlp


def call_via_module(v):
    return alpha.helper(v)


def call_via_from_alias(v):
    return hlp(v)


def call_via_import_alias(v):
    return qalpha.outer([v])


def build_engine(v):
    return alpha.Engine(v)


def passes_callback(xs):
    return list(map(local_cb, xs))


def local_cb(x):
    return x


def static_caller():
    return alpha.sized(4, True)


def tainted_caller(k):
    return alpha.sized(k, True)
