"""Callee side: helpers, nested closures (called and escaping), a class
with self-dispatch, a never-called function, and a static-args target."""


def helper(x):
    return x + 1


def outer(xs):
    def inner(v):
        return helper(v)

    return [inner(x) for x in xs]


def make_adder(n):
    def add(v):
        return v + n

    return add  # escapes by reference: runs in the caller's extent


class Engine:
    def __init__(self, k):
        self.k = k

    def step(self, v):
        return self._bump(v)

    def _bump(self, v):
        return helper(v) + self.k


def sized(n, flag):
    if flag:
        return [0] * n
    return []


def unreached(x):
    return helper(x)
