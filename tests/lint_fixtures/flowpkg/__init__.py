"""Known-answer fixture package for the qkflow engine (tests/test_flow.py
labels these files as the synthetic package ``quokka_tpu.flowfix`` so the
relative and absolute import forms below resolve; the files are parse-only
and never imported)."""

from .alpha import helper

helper(0)  # module-scope call site: static-argument propagation sees it
