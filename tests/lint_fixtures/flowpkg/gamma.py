"""Fully-dotted alias chain: ``import pkg.mod`` followed by
``pkg.mod.f()`` resolves through the root alias."""

import quokka_tpu.flowfix.alpha


def dotted_call(v):
    return quokka_tpu.flowfix.alpha.helper(v)


quokka_tpu.flowfix.alpha.sized(8, False)  # module-scope static call site
