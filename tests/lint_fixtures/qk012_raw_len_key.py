"""QK012 fixture: jit cache keys built from raw (un-bucketed) batch lengths.

Three findings: a sig-named tuple carrying .padded_len, a program-cache
.get() keyed on .shape[0], and a cache-subscript store keyed on
.padded_len.  Canonical keys must derive through quokka_tpu.ops.sigkey.
"""

_PROGRAMS = {}
_KERNEL_CACHE = {}


def lookup(batch, arr, fn):
    sig = (batch.padded_len, "f8")  # finding 1: raw length in a sig tuple
    hit = _PROGRAMS.get((arr.shape[0], "i4"))  # finding 2: raw .shape[0] key
    _KERNEL_CACHE[(batch.padded_len, "sum")] = fn  # finding 3: keyed store
    return sig, hit
