"""Seeded QK004: host syncs + python control flow in jit-reachable code.

The jit wrapper is built inside a function so this fixture does not also
trip QK001 — each fixture seeds exactly its own rule.
"""

import jax
import numpy as np


def _helper(x):
    # violation: reachable from the jitted entry via _kernel
    return np.asarray(x).sum()


def _kernel(x, flip):
    if flip:  # violation: python branch on a (non-static) parameter
        x = -x
    x.block_until_ready()  # violation: host sync inside traced code
    return _helper(x)


def make_kernel():
    return jax.jit(_kernel)
