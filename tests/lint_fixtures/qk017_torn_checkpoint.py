"""QK017 fixture: the checkpoint commit triple (LCT pointer, ckpts history
entry, IRT frontier) written WITHOUT a wrapping transaction — a crash
between the halves tears the frontier from its covering history.
``atomic_commit`` is the negative case and must NOT fire."""


def torn_commit(store, a, ch, state_seq, out_seq, tape_len):
    # QK017: both halves land outside any store.transaction() block
    store.tset("LCT", (a, ch), (state_seq, out_seq, tape_len))
    store.tappend("LT", ("ckpts", a, ch), (state_seq, out_seq, tape_len))


def atomic_commit(store, a, ch, state_seq, out_seq, tape_len, reqs):
    with store.transaction():
        store.tset("LCT", (a, ch), (state_seq, out_seq, tape_len))
        store.tappend("LT", ("ckpts", a, ch),
                      (state_seq, out_seq, tape_len))
        store.tset("IRT", (a, ch, state_seq), reqs)


def read_back(store, a, ch, state_seq):
    return (store.tget("LCT", (a, ch)),
            store.tget("LT", ("ckpts", a, ch)),
            store.tget("IRT", (a, ch, state_seq)))


def prune_history(store, a, ch, floor_state):
    # in-run GC for the growth classes this fixture writes (keeps the
    # fixture pure-QK017: no QK015 noise)
    hist = [h for h in (store.tget("LT", ("ckpts", a, ch)) or [])
            if h[0] >= floor_state]
    with store.transaction():
        # drop-and-reappend rewrite: exempt from the commit-triple check
        store.tdel("LT", ("ckpts", a, ch))
        for h in hist:
            store.tappend("LT", ("ckpts", a, ch), h)
        store.tdel("IRT", (a, ch, floor_state))
