"""Seeded QK002: import-time side effects."""

import os
import threading

from jax import monitoring


def _cb(event, **kw):
    pass  # inside a function: NOT an import-time effect (and QK006 ignores
    # non-except pass)


# violations: all of these run when the module is imported
monitoring.register_event_listener(_cb)
os.makedirs("/tmp/qk002_fixture", exist_ok=True)
_t = threading.Thread(target=_cb, daemon=True)
