"""QK014 fixture: control-store writes with no reader, and a per-query key
escaping the namespace wrapper.  ``well_paired``/``read_back`` are the
negative case — a written class with a reader must NOT fire."""


def record_unread(store, a, ch, digest):
    # QK014 dead-write: XRT is read nowhere — state nobody replays
    store.tset("XRT", (a, ch), digest)


def leak_raw(root_store, a, ch, payload):
    # QK014 namespace-escape: per-query lineage on the ROOT store — the
    # row outlives drop_namespace's sweep (also a dead write here)
    root_store.tset("LT", (a, ch, 0), payload)


def well_paired(store, a, ch, stamp):
    store.tset("XOK", (a, ch), stamp)


def read_back(store, a, ch):
    return store.tget("XOK", (a, ch))
