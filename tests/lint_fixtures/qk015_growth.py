"""QK015 fixture: a per-stream growth class with no in-run GC site.  The
``WRT`` rows are the negative case — per-seq growth WITH a tdel sweep must
NOT fire (the pairing manifest.gc provides for the real SWM/LT rows)."""


def append_history(store, a, ch, ev):
    # QK015: append-valued row grows with the stream, nothing reclaims it
    store.tappend("HGT", (a, ch), ev)


def read_history(store, a, ch):
    return store.tget("HGT", (a, ch))


def stamp_row(store, a, ch, seq, wm):
    store.tset("WRT", (a, ch, seq), wm)


def read_row(store, a, ch, seq):
    return store.tget("WRT", (a, ch, seq))


def gc_rows(store, a, ch, floor, base):
    for s in range(base, floor):
        store.tdel("WRT", (a, ch, s))
