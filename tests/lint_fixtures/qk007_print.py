"""QK007 fixture: bare print in library code (CLI main() is exempt)."""


def handle_batch(batch):
    print("processing", batch)  # QK007: route through obs.diag
    return batch


def main():
    print("usage: ...")  # exempt: CLI entry point
