"""Small-key one-hot-matmul group-by fast path (ops/fuse.py FusedPartialAgg)
vs the general sort+segment path: identical results on nulls-in-keys, empty
batches, single groups, and high-cardinality fallback."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from quokka_tpu import QuokkaContext
from quokka_tpu.ops import fuse


def run_agg(t, keys, aggs="sum(v) as sv, count(*) as n, count(v) as nv, avg(v) as av"):
    ctx = QuokkaContext()
    got = (
        ctx.from_arrow(t)
        .groupby(keys)
        .agg_sql(aggs)
        .collect()
        .sort_values(keys)
        .reset_index(drop=True)
    )
    return got


def oracle(t, keys):
    pdf = t.to_pandas()
    g = pdf.groupby(keys, dropna=False)
    out = g.agg(
        sv=("v", "sum"), n=("v", "size"), nv=("v", "count"), av=("v", "mean")
    ).reset_index()
    return out.sort_values(keys).reset_index(drop=True)


class TestSmallGroupby:
    def _table(self, n=20000, seed=0, null_keys=False, null_vals=True):
        r = np.random.default_rng(seed)
        flag = np.array(["A", "B", "C"], dtype=object)[r.integers(0, 3, n)]
        if null_keys:
            flag[r.random(n) < 0.05] = None
        v = r.uniform(0, 10, n).round(3)
        if null_vals:
            v[r.random(n) < 0.1] = np.nan
        return pa.table(
            {
                "flag": pa.array(flag, type=pa.string()),
                "status": np.array(["X", "Y"])[r.integers(0, 2, n)],
                "v": v,
            }
        )

    def _small_used(self):
        return any(k[0] == "partial_agg_small" for k in fuse._FUSED_PROGRAMS)

    def test_matches_oracle_with_null_values(self):
        t = self._table()
        got = run_agg(t, ["flag", "status"])
        exp = oracle(t, ["flag", "status"])
        assert self._small_used()
        np.testing.assert_allclose(got.sv.to_numpy(), exp.sv.to_numpy(), rtol=1e-9)
        assert got.n.tolist() == exp.n.tolist()
        assert got.nv.tolist() == exp.nv.tolist()
        np.testing.assert_allclose(got.av.to_numpy(), exp.av.to_numpy(), rtol=1e-9)

    def test_null_keys_form_one_group(self):
        t = self._table(null_keys=True)
        got = run_agg(t, ["flag"])
        exp = oracle(t, ["flag"])
        # pandas sorts NaN-keyed group last; ours yields None -> compare on
        # the non-null groups plus the null group's aggregate values
        got_nn = got[got.flag.notna()].reset_index(drop=True)
        exp_nn = exp[exp.flag.notna()].reset_index(drop=True)
        np.testing.assert_allclose(
            got_nn.sv.to_numpy(), exp_nn.sv.to_numpy(), rtol=1e-9
        )
        assert got_nn.n.tolist() == exp_nn.n.tolist()
        g_null = got[got.flag.isna()]
        e_null = exp[exp.flag.isna()]
        assert len(g_null) == len(e_null) == 1
        assert g_null.n.iloc[0] == e_null.n.iloc[0]
        np.testing.assert_allclose(
            g_null.sv.iloc[0], e_null.sv.iloc[0], rtol=1e-9
        )

    def test_single_group(self):
        t = pa.table({"flag": ["A"] * 1000, "status": ["X"] * 1000,
                      "v": np.arange(1000, dtype=np.float64)})
        got = run_agg(t, ["flag"])
        assert len(got) == 1
        assert got.sv.iloc[0] == float(np.arange(1000).sum())
        assert got.n.iloc[0] == 1000

    def test_integer_sum_stays_exact(self):
        r = np.random.default_rng(1)
        n = 30000
        t = pa.table(
            {
                "flag": np.array(["A", "B"])[r.integers(0, 2, n)],
                "q": r.integers(0, 1000, n),
                "v": r.uniform(0, 1, n),
            }
        )
        ctx = QuokkaContext()
        got = (
            ctx.from_arrow(t)
            .groupby("flag")
            .agg_sql("sum(q) as sq, count(*) as n")
            .collect()
            .sort_values("flag")
            .reset_index(drop=True)
        )
        exp = (
            t.to_pandas().groupby("flag").agg(sq=("q", "sum"), n=("q", "size"))
            .reset_index()
        )
        assert got.sq.tolist() == exp.sq.tolist()
        assert got.n.tolist() == exp.n.tolist()

    def test_high_cardinality_falls_back(self):
        r = np.random.default_rng(2)
        n = 5000
        # 500 distinct keys -> beyond _SMALL_GROUPBY_MAX_BUCKETS with the
        # second key, must fall back to the sort path and still be right
        k1 = np.array([f"k{i:04d}" for i in r.integers(0, 500, n)])
        t = pa.table({"flag": k1, "v": r.uniform(0, 10, n).round(3)})
        got = run_agg(t, ["flag"])
        exp = oracle(t, ["flag"])
        np.testing.assert_allclose(got.sv.to_numpy(), exp.sv.to_numpy(), rtol=1e-9)
        assert got.n.tolist() == exp.n.tolist()


class TestAdaptivePartialAgg:
    """Near-unique group keys flip PartialAggExecutor into passthrough
    (partial-FORM rows, no per-batch sort); results must be identical."""

    def _data(self, n=40_000, uniq=True, seed=5):
        import numpy as np
        import pyarrow as pa

        r = np.random.default_rng(seed)
        keys = (
            np.arange(n, dtype=np.int64) if uniq
            else r.integers(0, 50, n).astype(np.int64)
        )
        return pa.table({
            "k": r.permutation(keys),
            "v": r.uniform(0, 10, n).round(4),
            "w": r.integers(1, 9, n).astype(np.int64),
        })

    def _q(self, ctx, t, batch_rows):
        from quokka_tpu import logical
        from quokka_tpu.dataset.readers import InputArrowDataset

        src = ctx.new_stream(logical.SourceNode(
            InputArrowDataset(t, batch_rows=batch_rows), list(t.column_names)
        ))
        return (
            src.groupby("k")
            .agg_sql("sum(v) as sv, count(*) as n, avg(w) as aw, max(v) as mv")
            .collect()
            .sort_values("k")
            .reset_index(drop=True)
        )

    def test_unique_keys_match_pandas(self):
        import numpy as np

        from quokka_tpu import QuokkaContext

        t = self._data(uniq=True)
        d = t.to_pandas()
        ctx = QuokkaContext(io_channels=2, exec_channels=2)
        got = self._q(ctx, t, batch_rows=8192)
        exp = (
            d.groupby("k")
            .agg(sv=("v", "sum"), n=("v", "size"), aw=("w", "mean"),
                 mv=("v", "max"))
            .reset_index()
            .sort_values("k")
            .reset_index(drop=True)
        )
        assert len(got) == len(exp)
        np.testing.assert_array_equal(got.k.to_numpy(), exp.k.to_numpy())
        np.testing.assert_allclose(got.sv.to_numpy(), exp.sv.to_numpy(), rtol=1e-9)
        np.testing.assert_array_equal(got.n.to_numpy(), exp.n.to_numpy())
        np.testing.assert_allclose(got.aw.to_numpy(), exp.aw.to_numpy(), rtol=1e-9)
        np.testing.assert_allclose(got.mv.to_numpy(), exp.mv.to_numpy(), rtol=1e-9)

    def test_passthrough_decision(self):
        import pyarrow as pa

        from quokka_tpu.ops import bridge
        from quokka_tpu.ops.expr_compile import plan_aggregation
        from quokka_tpu.executors.sql_execs import PartialAggExecutor
        from quokka_tpu.sqlparse import parse_select_list

        plan = plan_aggregation(parse_select_list(
            "sum(v) as sv, count(*) as n"))
        # near-unique keys -> passthrough after batch 1
        t = self._data(n=10_000, uniq=True)
        ex = PartialAggExecutor(["k"], plan)
        b = bridge.arrow_to_device(t)
        assert ex.execute([b], 0, 0) is None  # batch 1 always aggregates
        assert ex._passthrough is True
        out = ex.execute([b], 0, 0)  # batch 2 passes through immediately
        assert out is not None and out.count_valid() == 10_000
        # low-cardinality keys -> stays aggregating
        t2 = self._data(n=10_000, uniq=False)
        ex2 = PartialAggExecutor(["k"], plan)
        b2 = bridge.arrow_to_device(t2)
        ex2.execute([b2], 0, 0)
        assert ex2._passthrough is False
        assert ex2.execute([b2], 0, 0) is None

    def test_checkpoint_carries_decision(self):
        from quokka_tpu.ops import bridge
        from quokka_tpu.ops.expr_compile import plan_aggregation
        from quokka_tpu.executors.sql_execs import PartialAggExecutor
        from quokka_tpu.sqlparse import parse_select_list

        plan = plan_aggregation(parse_select_list("count(*) as n"))
        t = self._data(n=10_000, uniq=True)
        ex = PartialAggExecutor(["k"], plan)
        ex.execute([bridge.arrow_to_device(t)], 0, 0)
        snap = ex.checkpoint()
        ex2 = PartialAggExecutor(["k"], plan)
        ex2.restore(snap)
        assert ex2._passthrough is True
