"""Fault-tolerance tests: kill exec workers mid-query (losing their state,
queued tasks, and cached inputs), recover from HBQ spill + checkpoints, and
assert results identical to an undisturbed run — the scripted version of the
reference's manual instance-kill testing (SURVEY.md sections 4/5)."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from quokka_tpu import QuokkaContext
from quokka_tpu.dataset.readers import InputArrowDataset


def make_data(n=20_000, seed=2):
    r = np.random.default_rng(seed)
    return pa.table(
        {
            "k": r.integers(0, 50, n).astype(np.int64),
            "v": r.normal(size=n),
            "s": np.array(["x", "y", "z"])[r.integers(0, 3, n)],
        }
    )


def agg_query(ctx, table, **cfg):
    for key, val in cfg.items():
        ctx.set_config(key, val)
    s = ctx.read_dataset(InputArrowDataset(table, batch_rows=1024))
    return (
        s.groupby("k")
        .agg_sql("sum(v) as sv, count(*) as n")
        .collect()
        .sort_values("k")
        .reset_index(drop=True)
    )


class TestRecovery:
    def test_agg_survives_partial_agg_failure(self, tmp_path):
        table = make_data()
        baseline = agg_query(QuokkaContext(), table)
        ctx = QuokkaContext()
        got = agg_query(
            ctx,
            table,
            fault_tolerance=True,
            hbq_path=str(tmp_path),
            checkpoint_interval=3,
            inject_failure={"after_tasks": 12, "channels": [(1, 0)]},  # partial agg ch 0
        )
        pd.testing.assert_frame_equal(got, baseline, rtol=1e-9, check_dtype=False)

    def test_agg_survives_failure_without_checkpoint(self, tmp_path):
        table = make_data()
        baseline = agg_query(QuokkaContext(), table)
        ctx = QuokkaContext()
        got = agg_query(
            ctx,
            table,
            fault_tolerance=True,
            hbq_path=str(tmp_path),
            checkpoint_interval=None,  # full rewind to state 0 via HBQ replay
            inject_failure={"after_tasks": 10, "channels": [(1, 0), (1, 1)]},
        )
        pd.testing.assert_frame_equal(got, baseline, rtol=1e-9, check_dtype=False)

    def test_join_survives_probe_failure(self, tmp_path):
        r = np.random.default_rng(4)
        left = pa.table(
            {"key": r.integers(0, 200, 8000).astype(np.int64), "x": r.normal(size=8000)}
        )
        right = pa.table(
            {"key": np.arange(0, 150, dtype=np.int64), "y": r.normal(size=150)}
        )

        def q(ctx, **cfg):
            for k, v in cfg.items():
                ctx.set_config(k, v)
            ls = ctx.read_dataset(InputArrowDataset(left, batch_rows=512))
            rs = ctx.read_dataset(InputArrowDataset(right, batch_rows=64))
            return (
                ls.join(rs, on="key")
                .groupby("key")
                .agg_sql("sum(x * y) as t, count(*) as n")
                .collect()
                .sort_values("key")
                .reset_index(drop=True)
            )

        baseline = q(QuokkaContext(optimize=False))
        ctx = QuokkaContext(optimize=False)
        # actor 2 is the join (actors: 0 left src, 1 right src, 2 join, ...)
        got = q(
            ctx,
            fault_tolerance=True,
            hbq_path=str(tmp_path),
            checkpoint_interval=4,
            inject_failure={"after_tasks": 15, "channels": [(2, 0)]},
        )
        pd.testing.assert_frame_equal(got, baseline, rtol=1e-9, check_dtype=False)

    def test_failure_of_noncheckpointable_executor(self, tmp_path):
        # FinalAggExecutor has no checkpoint support: the runtime must NOT
        # record a recovery point for it (regression: a fresh executor was
        # restored at a checkpointed frontier, silently dropping groups)
        table = make_data()
        baseline = agg_query(QuokkaContext(), table)
        ctx = QuokkaContext()
        got = agg_query(
            ctx,
            table,
            fault_tolerance=True,
            hbq_path=str(tmp_path),
            checkpoint_interval=2,
            inject_failure={"after_tasks": 25, "channels": [(2, 0)]},  # final agg
        )
        pd.testing.assert_frame_equal(got, baseline, rtol=1e-9, check_dtype=False)

    def test_failure_requires_ft_enabled(self):
        from quokka_tpu.runtime.engine import Engine, TaskGraph

        g = TaskGraph()
        e = Engine(g)
        with pytest.raises(AssertionError):
            e.simulate_failure_and_recover([(0, 0)])


class TestHBQ:
    def test_put_get_gc(self, tmp_path):
        from quokka_tpu.runtime.hbq import HBQ

        hbq = HBQ(str(tmp_path / "h"))
        t = pa.table({"a": [1, 2, 3]})
        name = (0, 1, 2, 3, 0, 4)
        hbq.put(name, t)
        assert hbq.contains(name)
        back = hbq.get(name)
        assert back.equals(t)
        hbq.gc([name])
        assert not hbq.contains(name)
        assert hbq.get(name) is None


class TestRewindPlanner:
    """plan_rewinds (engine.py): need-driven checkpoint selection when a
    consumer's tape references a CO-DEAD producer's outputs from before that
    producer's latest checkpoint (reference: coordinator.py:221-229)."""

    def _store(self):
        from quokka_tpu.runtime.tables import ControlStore

        return ControlStore()

    def _ckpt(self, cs, a, ch, entries):
        for e in entries:
            cs.tappend("LT", ("ckpts", a, ch), e)
        cs.tset("LCT", (a, ch), entries[-1])

    def test_latest_checkpoint_when_producers_alive(self):
        from quokka_tpu.runtime.engine import plan_rewinds

        cs = self._store()
        self._ckpt(cs, 3, 0, [(2, 5, 4), (4, 9, 8)])
        # tape consumes only from actor 1 (NOT dead): no rewind needed
        cs.tappend("LT", ("tape", 3, 0),
                   ("exec", 1, [(1, 0, 9, 3, 1, 0)], True))
        out = plan_rewinds(cs, [(3, 0)])
        assert out[(3, 0)] == (4, 9, 8)

    def test_codead_producer_rewinds_to_covering_checkpoint(self):
        from quokka_tpu.runtime.engine import plan_rewinds

        cs = self._store()
        # producer (2,0): checkpoints at out_seq 5 and 9
        self._ckpt(cs, 2, 0, [(2, 5, 4), (4, 9, 8)])
        # consumer (3,0): no checkpoint; its tape (from pos 0) consumed
        # producer output seq 6 — covered by (2,5,4) but not (4,9,8)
        cs.tappend("LT", ("tape", 3, 0),
                   ("exec", 2, [(2, 0, 6, 3, 2, 0)], True))
        out = plan_rewinds(cs, [(2, 0), (3, 0)])
        assert out[(3, 0)] == (0, 0, 0)
        assert out[(2, 0)] == (2, 5, 4)

    def test_transitive_rewind_to_state_zero(self):
        from quokka_tpu.runtime.engine import plan_rewinds

        cs = self._store()
        self._ckpt(cs, 1, 0, [(3, 7, 6)])
        self._ckpt(cs, 2, 0, [(2, 5, 4)])
        # consumer (3,0) needs (2,0) seq 1 -> (2,0) rewinds to 0; the
        # EXTENDED tape of (2,0) then needs (1,0) seq 2 -> (1,0) rewinds to 0
        cs.tappend("LT", ("tape", 3, 0),
                   ("exec", 2, [(2, 0, 1, 3, 2, 0)], True))
        cs.tappend("LT", ("tape", 2, 0),
                   ("exec", 1, [(1, 0, 2, 2, 1, 0)], True))
        cs.tappend("LT", ("tape", 2, 0),
                   ("exec", 1, [(1, 0, 8, 2, 1, 0)], True))
        out = plan_rewinds(cs, [(1, 0), (2, 0), (3, 0)])
        assert out[(2, 0)] == (0, 0, 0)
        assert out[(1, 0)] == (0, 0, 0)
