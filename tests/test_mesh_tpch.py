"""All 22 TPC-H queries under QuokkaContext(mesh=8-device CPU mesh): plans
the mesh path supports run SPMD (shard_map + all_to_all); the rest fall back
to the embedded engine via the pre-walk.  Either way results must equal the
plain-context run — this pins the fallback boundary and the SPMD kernels
against the full query corpus."""

import numpy as np
import pandas as pd
import pytest

from quokka_tpu import QuokkaContext
from quokka_tpu.parallel.mesh import make_mesh

import tpch_data


@pytest.fixture(scope="module")
def env(tmp_path_factory):
    root = tmp_path_factory.mktemp("mesh_tpch")
    tables = tpch_data.generate(sf=0.0015, seed=23)
    paths = tpch_data.write_parquet_dir(tables, str(root))
    return paths


def _q3(ctx, s):
    return (
        s["lineitem"].filter_sql("l_shipdate > date '1995-03-15'")
        .join(s["orders"].filter_sql("o_orderdate < date '1995-03-15'"),
              left_on="l_orderkey", right_on="o_orderkey")
        .join(s["customer"].filter_sql("c_mktsegment = 'BUILDING'"),
              left_on="o_custkey", right_on="c_custkey")
        .groupby("l_orderkey")
        .agg_sql("sum(l_extendedprice * (1 - l_discount)) as revenue")
        .collect()
    )


def _q1(ctx, s):
    return (
        s["lineitem"].filter_sql("l_shipdate <= date '1998-09-02'")
        .groupby(["l_returnflag", "l_linestatus"])
        .agg_sql("sum(l_quantity) as sq, avg(l_discount) as ad, count(*) as n")
        .collect()
    )


def _q5(ctx, s):
    nat = s["nation"].join(
        s["region"].filter_sql("r_name = 'ASIA'"),
        left_on="n_regionkey", right_on="r_regionkey", how="semi")
    return (
        s["lineitem"]
        .join(s["orders"], left_on="l_orderkey", right_on="o_orderkey")
        .join(s["customer"], left_on="o_custkey", right_on="c_custkey")
        .join(s["supplier"], left_on="l_suppkey", right_on="s_suppkey")
        .join(nat, left_on="s_nationkey", right_on="n_nationkey")
        .filter_sql("c_nationkey = s_nationkey")
        .groupby("n_name")
        .agg_sql("sum(l_extendedprice * (1 - l_discount)) as revenue")
        .collect()
    )


def _q6(ctx, s):
    return (
        s["lineitem"].filter_sql(
            "l_shipdate >= date '1994-01-01' and l_shipdate < date '1995-01-01' "
            "and l_discount between 0.05 and 0.07 and l_quantity < 24")
        .agg_sql("sum(l_extendedprice * l_discount) as revenue")
        .collect()
    )


def _q10(ctx, s):
    return (
        s["lineitem"].filter_sql("l_returnflag = 'R'")
        .join(s["orders"].filter_sql(
            "o_orderdate >= date '1993-10-01' and o_orderdate < date '1994-01-01'"),
            left_on="l_orderkey", right_on="o_orderkey")
        .join(s["customer"], left_on="o_custkey", right_on="c_custkey")
        .groupby(["o_custkey", "c_name"])
        .agg_sql("sum(l_extendedprice * (1 - l_discount)) as revenue")
        .top_k(["revenue"], 20, descending=[True])
        .collect()
    )


def _q12(ctx, s):
    return (
        s["lineitem"].filter_sql(
            "l_shipmode in ('MAIL', 'SHIP') and l_commitdate < l_receiptdate "
            "and l_shipdate < l_commitdate and "
            "l_receiptdate >= date '1994-01-01' and l_receiptdate < date '1995-01-01'")
        .join(s["orders"], left_on="l_orderkey", right_on="o_orderkey")
        .with_columns_sql(
            "case when o_orderpriority = '1-URGENT' or o_orderpriority = '2-HIGH' "
            "then 1.0 else 0.0 end as high")
        .groupby("l_shipmode")
        .agg_sql("sum(high) as high_count, count(*) as n")
        .collect()
    )


def _q14(ctx, s):
    return (
        s["lineitem"].filter_sql(
            "l_shipdate >= date '1995-09-01' and l_shipdate < date '1995-10-01'")
        .join(s["part"], left_on="l_partkey", right_on="p_partkey")
        .with_columns_sql(
            "case when p_type like 'PROMO%' "
            "then l_extendedprice * (1 - l_discount) else 0.0 end as promo, "
            "l_extendedprice * (1 - l_discount) as rev")
        .agg_sql("100.0 * sum(promo) / sum(rev) as promo_revenue")
        .collect()
    )


def _q18(ctx, s):
    big = (s["lineitem"].groupby("l_orderkey")
           .agg_sql("sum(l_quantity) as sq").filter_sql("sq > 250"))
    return (
        s["orders"]
        .join(big.rename({"l_orderkey": "b_ok"}), left_on="o_orderkey", right_on="b_ok")
        .join(s["customer"], left_on="o_custkey", right_on="c_custkey")
        .select(["c_name", "o_orderkey", "sq"])
        .collect()
    )


def _q19(ctx, s):
    return (
        s["lineitem"].filter_sql("l_shipmode in ('AIR', 'REG AIR')")
        .join(s["part"].filter_sql("p_size between 1 and 15"),
              left_on="l_partkey", right_on="p_partkey")
        .filter_sql("l_quantity >= 1 and l_quantity <= 30")
        .agg_sql("sum(l_extendedprice * (1 - l_discount)) as revenue")
        .collect()
    )


QUERIES = {
    "q1": _q1, "q3": _q3, "q5": _q5, "q6": _q6, "q10": _q10,
    "q12": _q12, "q14": _q14, "q18": _q18, "q19": _q19,
}


@pytest.mark.parametrize("name", sorted(QUERIES))
def test_mesh_equals_engine(env, name):
    paths = env
    mesh = make_mesh()

    def run(ctx):
        s = {k: ctx.read_parquet(p) for k, p in paths.items()}
        return QUERIES[name](ctx, s)

    mctx = QuokkaContext(mesh=mesh)
    got = run(mctx)
    # these shapes must actually execute SPMD, not silently fall back
    assert mctx.last_mesh_fallback is None, mctx.last_mesh_fallback
    exp = run(QuokkaContext())
    got = got.sort_values(list(got.columns)).reset_index(drop=True)
    exp = exp.sort_values(list(exp.columns)).reset_index(drop=True)
    pd.testing.assert_frame_equal(got, exp, check_dtype=False, rtol=1e-9)


@pytest.mark.slow
def test_full_corpus_runs_on_mesh(env):
    """Every one of the 22 TPC-H oracle queries (tests/test_tpch*.py shapes)
    executes ON the mesh — zero fallbacks across the corpus.  Results are
    pinned against the engine by those suites' own oracles; here the claim
    under test is COVERAGE of the SPMD path.  ~6 min => slow tier."""
    import test_tpch as T1
    import test_tpch2 as T2
    import tpch_data as TD

    root = str(env["lineitem"]).rsplit("/", 1)[0]
    tables = TD.generate(sf=0.003, seed=11)
    paths = TD.write_parquet_dir(tables, root)
    dfs = {k: t.to_pandas() for k, t in tables.items()}
    mesh = make_mesh()
    fallbacks = {}
    for mod in (T1, T2):
        for name in dir(mod):
            if not name.startswith("test_q"):
                continue
            ctx = QuokkaContext(mesh=mesh, io_channels=2, exec_channels=2)
            getattr(mod, name)((ctx, paths, dfs))
            if ctx.last_mesh_fallback is not None:
                fallbacks[name] = ctx.last_mesh_fallback
    assert not fallbacks, fallbacks
