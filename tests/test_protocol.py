"""Control-store protocol verifier (analysis/protocol.py, QK014-QK017).

Fixture-driven positive cases, negative (must-not-fire) cases baked into
the same fixtures, the tree-clean gate, and the CLI contract (nonzero on
violations, NO baseline)."""

import os
import subprocess
import sys

import pytest

from quokka_tpu.analysis.protocol import main, render_matrix, verify

FIXTURES = os.path.join(os.path.dirname(__file__), "lint_fixtures")
PKG = os.path.dirname(
    os.path.dirname(os.path.abspath(verify.__code__.co_filename)))

CASES = [
    # (rule, fixture, expected finding count)
    ("QK014", "qk014_dead_write.py", 3),   # XRT dead + escape site (2 ways)
    ("QK015", "qk015_growth.py", 1),       # HGT append, WRT pair is clean
    ("QK016", "qk016_lock_cycle.py", 1),   # alpha<->beta cycle
    ("QK017", "qk017_torn_checkpoint.py", 2),  # LCT half + ckpts half
]


@pytest.mark.parametrize("rule,fixture,expected", CASES,
                         ids=[c[0] for c in CASES])
def test_rule_fires_on_fixture(rule, fixture, expected):
    findings, _ops = verify([os.path.join(FIXTURES, fixture)])
    mine = [f for f in findings if f.rule == rule]
    assert len(mine) == expected, [f.render() for f in findings]
    # single-rule fixtures: no cross-rule noise
    assert {f.rule for f in findings} == {rule}, \
        [f.render() for f in findings]


def test_qk014_slugs_cover_both_checks():
    findings, _ = verify([os.path.join(FIXTURES, "qk014_dead_write.py")])
    assert {f.name for f in findings} == {"dead-write", "namespace-escape"}


def test_tree_is_protocol_clean():
    """The shipped package holds the protocol invariants — there is NO
    baseline for QK014-QK017; a regression fails here."""
    findings, ops = verify([PKG])
    assert findings == [], [f.render() for f in findings]
    # the matrix actually extracted the store surface (sanity that a
    # refactor of receiver naming doesn't silently blind the verifier)
    tables = {o.keyclass[0] for o in ops}
    for expected in ("LT", "IRT", "SWM", "LCT", "GIT", "NTT"):
        assert expected in tables, sorted(tables)


def test_growth_classes_all_have_gc():
    """Every growth-class write in the tree is paired with an in-run GC
    site (the QK015 guarantee manifest.gc provides for streams)."""
    _findings, ops = verify([PKG])
    growth = {o.keyclass for o in ops if o.kind == "write" and o.growth}
    assert growth, "growth classes disappeared — extraction regressed?"
    gc_classes = [o.keyclass for o in ops
                  if o.kind == "gc" and o.method != "drop_namespace"]
    from quokka_tpu.analysis.protocol import _classes_match
    for g in growth:
        assert any(_classes_match(g, c) for c in gc_classes), g


def test_matrix_renders():
    _findings, ops = verify([PKG])
    text = render_matrix(ops)
    assert "key-class" in text and "growth" in text
    assert "LT('ckpts', _, _)" in text


def test_cli_exit_codes(tmp_path):
    assert main([PKG]) == 0
    assert main([os.path.join(FIXTURES, "qk015_growth.py")]) == 1
    # module entry point (what `make verify-static` runs)
    r = subprocess.run(
        [sys.executable, "-m", "quokka_tpu.analysis.protocol", PKG,
         "--matrix"],
        capture_output=True, text=True,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert r.returncode == 0, r.stderr
    assert "key-class" in r.stdout
