"""Mesh execution (channels == shards): the same DataStream queries run SPMD
over the virtual 8-device CPU mesh and must equal the embedded-engine result.
This is the multi-chip path VERDICT r1 item 2 asked to be the engine, not a
demo — sources shard rows, joins/groupbys run as one shard_map with an
all_to_all key shuffle (parallel/mesh_exec.py)."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from quokka_tpu import QuokkaContext
from quokka_tpu.parallel.mesh import make_mesh


@pytest.fixture(scope="module")
def mesh():
    return make_mesh()


def tiny_tpch(tmp_path_factory):
    r = np.random.default_rng(7)
    n_cust, n_ord, n_li = 200, 1000, 4000
    customer = pa.table(
        {
            "c_custkey": np.arange(n_cust, dtype=np.int64),
            "c_mktsegment": np.array(["BUILDING", "MACHINERY", "AUTOMOBILE"])[
                r.integers(0, 3, n_cust)
            ],
        }
    )
    orders = pa.table(
        {
            "o_orderkey": np.arange(n_ord, dtype=np.int64),
            "o_custkey": r.integers(0, n_cust, n_ord).astype(np.int64),
            "o_orderdate": pa.array(
                r.integers(9000, 10000, n_ord).astype(np.int32), type=pa.int32()
            ).cast(pa.date32()),
        }
    )
    lineitem = pa.table(
        {
            "l_orderkey": r.integers(0, n_ord, n_li).astype(np.int64),
            "l_extendedprice": r.uniform(100, 5000, n_li).round(2),
            "l_discount": r.uniform(0, 0.1, n_li).round(3),
            "l_shipdate": pa.array(
                r.integers(9000, 10000, n_li).astype(np.int32), type=pa.int32()
            ).cast(pa.date32()),
        }
    )
    return customer, orders, lineitem


@pytest.fixture(scope="module")
def tpch_tables(tmp_path_factory):
    return tiny_tpch(tmp_path_factory)


def q3(ctx, customer, orders, lineitem):
    c = ctx.from_arrow(customer).filter_sql("c_mktsegment = 'BUILDING'")
    o = ctx.from_arrow(orders).filter_sql("o_orderdate < date '1996-06-01'")
    l = ctx.from_arrow(lineitem).filter_sql("l_shipdate > date '1995-01-01'")
    return (
        l.join(o, left_on="l_orderkey", right_on="o_orderkey")
        .join(c, left_on="o_custkey", right_on="c_custkey")
        .groupby("l_orderkey")
        .agg_sql("sum(l_extendedprice * (1 - l_discount)) as revenue")
        .collect()
    )


class TestMeshMatchesEngine:
    def test_q3_shape(self, mesh, tpch_tables):
        customer, orders, lineitem = tpch_tables
        got = q3(QuokkaContext(mesh=mesh), customer, orders, lineitem)
        exp = q3(QuokkaContext(), customer, orders, lineitem)
        got = got.sort_values("l_orderkey").reset_index(drop=True)
        exp = exp.sort_values("l_orderkey").reset_index(drop=True)
        assert len(got) == len(exp)
        np.testing.assert_array_equal(
            got.l_orderkey.to_numpy(), exp.l_orderkey.to_numpy()
        )
        np.testing.assert_allclose(
            got.revenue.to_numpy(), exp.revenue.to_numpy(), rtol=1e-9
        )

    def test_groupby_string_key(self, mesh, tpch_tables):
        customer, orders, lineitem = tpch_tables
        def q(ctx):
            return (
                ctx.from_arrow(customer)
                .groupby("c_mktsegment")
                .agg_sql("count(*) as n")
                .collect()
                .sort_values("c_mktsegment")
                .reset_index(drop=True)
            )
        got, exp = q(QuokkaContext(mesh=mesh)), q(QuokkaContext())
        assert got.c_mktsegment.tolist() == exp.c_mktsegment.tolist()
        assert got.n.tolist() == exp.n.tolist()

    def test_semi_anti_left(self, mesh, tpch_tables):
        customer, orders, _ = tpch_tables
        for how in ("semi", "anti", "left", "inner"):
            def q(ctx):
                o = ctx.from_arrow(orders)
                c = ctx.from_arrow(customer).filter_sql(
                    "c_mktsegment = 'MACHINERY'"
                )
                out = o.join(c, left_on="o_custkey", right_on="c_custkey",
                             how=how).collect()
                return out.sort_values("o_orderkey").reset_index(drop=True)
            got, exp = q(QuokkaContext(mesh=mesh)), q(QuokkaContext())
            assert len(got) == len(exp), how
            np.testing.assert_array_equal(
                got.o_orderkey.to_numpy(), exp.o_orderkey.to_numpy(), err_msg=how
            )
            if how == "left":
                np.testing.assert_array_equal(
                    got.c_mktsegment.isna().to_numpy(),
                    exp.c_mktsegment.isna().to_numpy(),
                )

    def test_agg_with_orderby_limit(self, mesh, tpch_tables):
        _, orders, lineitem = tpch_tables
        def q(ctx):
            return (
                ctx.from_arrow(lineitem)
                .groupby("l_orderkey")
                .agg_sql("sum(l_extendedprice) as total")
                .top_k(["total"], 5, descending=[True])
                .collect()
                .reset_index(drop=True)
            )
        got, exp = q(QuokkaContext(mesh=mesh)), q(QuokkaContext())
        np.testing.assert_allclose(got.total.to_numpy(), exp.total.to_numpy())

    def test_keyless_agg(self, mesh, tpch_tables):
        _, _, lineitem = tpch_tables
        def q(ctx):
            return (
                ctx.from_arrow(lineitem)
                .agg_sql("sum(l_extendedprice) as s, count(*) as n, "
                         "avg(l_discount) as a")
                .collect()
            )
        got, exp = q(QuokkaContext(mesh=mesh)), q(QuokkaContext())
        np.testing.assert_allclose(got.s[0], exp.s[0], rtol=1e-9)
        assert got.n[0] == exp.n[0]
        np.testing.assert_allclose(got.a[0], exp.a[0], rtol=1e-9)

    def test_unsupported_plan_falls_back(self, mesh):
        # asof join lowers to a StatefulNode — pre-walk must fall back to the
        # embedded engine without executing anything on the mesh
        trades = pa.table({"time": np.arange(10, dtype=np.int64),
                           "sym": ["A"] * 10})
        quotes = pa.table({"time": np.arange(0, 10, 2, dtype=np.int64),
                           "sym": ["A"] * 5,
                           "bid": np.arange(5).astype(np.float64)})
        ctx = QuokkaContext(mesh=mesh)
        t = ctx.from_arrow_sorted(trades, sorted_by="time")
        q = ctx.from_arrow_sorted(quotes, sorted_by="time")
        got = t.join_asof(q, on="time", by="sym").collect()
        assert len(got) == 10

    def test_distinct(self, mesh, tpch_tables):
        _, orders, _ = tpch_tables
        def q(ctx):
            return (
                ctx.from_arrow(orders)
                .select(["o_custkey"])
                .distinct()
                .collect()
                .sort_values("o_custkey")
                .reset_index(drop=True)
            )
        got, exp = q(QuokkaContext(mesh=mesh)), q(QuokkaContext())
        np.testing.assert_array_equal(
            got.o_custkey.to_numpy(), exp.o_custkey.to_numpy()
        )


class TestMeshManyToMany:
    def test_mm_inner_and_left(self, mesh):
        r = np.random.default_rng(21)
        # duplicate build keys -> mm path (PK kernel would be wrong)
        build = pa.table({
            "k": r.integers(0, 50, 300).astype(np.int64),
            "w": r.uniform(0, 1, 300).round(5),
        })
        probe = pa.table({
            "k": r.integers(0, 100, 800).astype(np.int64),  # half miss
            "v": r.uniform(0, 1, 800).round(5),
        })
        for how in ("inner", "left"):
            def q(ctx):
                return (
                    ctx.from_arrow(probe)
                    .join(ctx.from_arrow(build), on="k", how=how)
                    .collect()
                )
            got = q(QuokkaContext(mesh=mesh))
            exp = probe.to_pandas().merge(build.to_pandas(), on="k", how=how)
            assert len(got) == len(exp), how
            np.testing.assert_allclose(got.v.sum(), exp.v.sum(), rtol=1e-9)
            np.testing.assert_allclose(
                got.w.sum(), exp.w.dropna().sum(), rtol=1e-9, err_msg=how
            )
            if how == "left":
                assert got.w.isna().sum() == exp.w.isna().sum()

    def test_mm_overflow_falls_back(self, mesh, monkeypatch):
        import quokka_tpu.parallel.mesh_exec as mx

        monkeypatch.setattr(mx, "MM_CAPACITY_FACTOR", 1)
        # heavy fanout: every probe row matches ~40 build rows -> overflow
        build = pa.table({"k": np.zeros(40, dtype=np.int64),
                          "w": np.arange(40).astype(np.float64)})
        probe = pa.table({"k": np.zeros(100, dtype=np.int64),
                          "v": np.arange(100).astype(np.float64)})
        ctx = QuokkaContext(mesh=mesh)
        got = (
            ctx.from_arrow(probe)
            .join(ctx.from_arrow(build), on="k")
            .collect()
        )
        assert len(got) == 4000  # engine fallback produced the full product
