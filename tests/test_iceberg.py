"""Avro container reader (dataset/avro.py) and the Iceberg metadata walk
(dataset/iceberg.py): golden-byte fixtures (so the reader is not validated
only against the test's own encoder), an encoder round trip, and end-to-end
read_iceberg queries with time travel and deleted data files."""

import json
import os
import struct
import zlib

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from quokka_tpu import QuokkaContext
from quokka_tpu.dataset import avro
from quokka_tpu.dataset.iceberg import IcebergTable

SYNC = b"0123456789abcdef"


# --- tiny spec-following Avro encoder (test-side only) ----------------------

def zz(n: int) -> bytes:
    u = (n << 1) ^ (n >> 63) if n < 0 else (n << 1)
    u &= (1 << 64) - 1
    out = bytearray()
    while True:
        b = u & 0x7F
        u >>= 7
        if u:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def enc_bytes(b: bytes) -> bytes:
    return zz(len(b)) + b


def enc_str(s: str) -> bytes:
    return enc_bytes(s.encode())


def encode(schema, datum) -> bytes:
    if isinstance(schema, list):  # union
        for i, branch in enumerate(schema):
            t = branch if isinstance(branch, str) else branch["type"]
            if datum is None and t == "null":
                return zz(i)
            if datum is not None and t != "null":
                return zz(i) + encode(branch, datum)
        raise ValueError("no union branch")
    t = schema if isinstance(schema, str) else schema["type"]
    if t == "null":
        return b""
    if t == "boolean":
        return b"\x01" if datum else b"\x00"
    if t in ("int", "long"):
        return zz(int(datum))
    if t == "float":
        return struct.pack("<f", datum)
    if t == "double":
        return struct.pack("<d", datum)
    if t == "bytes":
        return enc_bytes(datum)
    if t == "string":
        return enc_str(datum)
    if t == "record":
        return b"".join(encode(f["type"], datum[f["name"]]) for f in schema["fields"])
    if t == "array":
        out = b""
        if datum:
            out += zz(len(datum))
            out += b"".join(encode(schema["items"], d) for d in datum)
        return out + zz(0)
    if t == "map":
        out = b""
        if datum:
            out += zz(len(datum))
            out += b"".join(enc_str(k) + encode(schema["values"], v)
                            for k, v in datum.items())
        return out + zz(0)
    if t == "enum":
        return zz(schema["symbols"].index(datum))
    if t == "fixed":
        assert len(datum) == schema["size"]
        return datum
    raise ValueError(t)


def write_container(path, schema, records, codec="null"):
    sj = json.dumps(schema).encode()
    meta = b"".join([
        zz(2),
        enc_str("avro.codec"), enc_bytes(codec.encode()),
        enc_str("avro.schema"), enc_bytes(sj),
        zz(0),
    ])
    payload = b"".join(encode(schema, r) for r in records)
    if codec == "deflate":
        c = zlib.compressobj(wbits=-15)
        payload = c.compress(payload) + c.flush()
    blob = avro.MAGIC + meta + SYNC + zz(len(records)) + zz(len(payload)) + payload + SYNC
    with open(path, "wb") as f:
        f.write(blob)
    return path


# --- golden bytes (hand-assembled, independent of the encoder above) --------

GOLDEN_SCHEMA = (
    b'{"type":"record","name":"R","fields":'
    b'[{"name":"a","type":"long"},{"name":"b","type":"string"}]}'
)


def golden_file() -> bytes:
    meta = (
        b"\x04"                                  # map block: 2 entries
        + b"\x14avro.codec" + b"\x08null"        # "avro.codec" -> "null"
        + b"\x16avro.schema"                     # "avro.schema"
        + zz(len(GOLDEN_SCHEMA)) + GOLDEN_SCHEMA
        + b"\x00"                                # end of map
    )
    payload = b"\x06\x04hi" + b"\x01\x00"        # {a:3,b:"hi"}, {a:-1,b:""}
    return (
        b"Obj\x01" + meta + SYNC
        + b"\x04"                                # block: 2 records
        + b"\x0c"                                # 6 payload bytes
        + payload + SYNC
    )


class TestAvro:
    def test_golden_bytes(self):
        records, meta = avro.read_file(golden_file())
        assert records == [{"a": 3, "b": "hi"}, {"a": -1, "b": ""}]
        assert meta["avro.codec"] == b"null"

    def test_roundtrip_rich_schema(self, tmp_path):
        schema = {
            "type": "record", "name": "E", "fields": [
                {"name": "id", "type": "long"},
                {"name": "opt", "type": ["null", "string"]},
                {"name": "tags", "type": {"type": "array", "items": "string"}},
                {"name": "props", "type": {"type": "map", "values": "long"}},
                {"name": "kind", "type": {"type": "enum", "name": "K",
                                          "symbols": ["X", "Y"]}},
                {"name": "raw", "type": "bytes"},
                {"name": "f", "type": "double"},
                {"name": "ok", "type": "boolean"},
            ],
        }
        records = [
            {"id": 1, "opt": None, "tags": ["a", "b"], "props": {"n": 2},
             "kind": "X", "raw": b"\x00\xff", "f": 2.5, "ok": True},
            {"id": -(2**40), "opt": "s", "tags": [], "props": {},
             "kind": "Y", "raw": b"", "f": -0.125, "ok": False},
        ]
        p = write_container(str(tmp_path / "r.avro"), schema, records)
        got, _ = avro.read_path(p)
        assert got == records

    def test_deflate_codec(self, tmp_path):
        schema = {"type": "record", "name": "D",
                  "fields": [{"name": "x", "type": "long"}]}
        records = [{"x": i} for i in range(100)]
        p = write_container(str(tmp_path / "d.avro"), schema, records,
                            codec="deflate")
        got, meta = avro.read_path(p)
        assert got == records
        assert meta["avro.codec"] == b"deflate"

    def test_bad_magic_raises(self):
        with pytest.raises(avro.AvroError, match="container"):
            avro.read_file(b"NOPE" + b"\x00" * 40)

    def test_negative_block_header_raises(self):
        """A corrupt/crafted negative block size must fail loudly instead of
        rewinding the reader and misaligning decoding."""
        good = golden_file()
        # Splice a negative block count (-1 zigzag = 0x01) where the block
        # header starts (right after the 16-byte sync following metadata).
        idx = good.index(SYNC) + 16
        bad = good[:idx] + zz(-1) + good[idx + 1:]
        with pytest.raises(avro.AvroError, match="corrupt block header"):
            avro.read_file(bad)


# --- Iceberg fixture ---------------------------------------------------------

MANIFEST_SCHEMA = {
    "type": "record", "name": "manifest_entry", "fields": [
        {"name": "status", "type": "int"},
        {"name": "snapshot_id", "type": ["null", "long"]},
        {"name": "data_file", "type": {
            "type": "record", "name": "r2", "fields": [
                {"name": "file_path", "type": "string"},
                {"name": "file_format", "type": "string"},
                {"name": "record_count", "type": "long"},
                {"name": "file_size_in_bytes", "type": "long"},
            ]}},
    ],
}

MANIFEST_LIST_SCHEMA = {
    "type": "record", "name": "manifest_file", "fields": [
        {"name": "manifest_path", "type": "string"},
        {"name": "manifest_length", "type": "long"},
        {"name": "partition_spec_id", "type": "int"},
    ],
}


def build_iceberg_table(root):
    """Two snapshots: s1 = {f1, f2}; s2 adds f3 and DELETES f1."""
    loc = f"file://{root}"
    os.makedirs(os.path.join(root, "data"))
    os.makedirs(os.path.join(root, "metadata"))
    r = np.random.default_rng(9)

    def write_data(name, n, base):
        t = pa.table({
            "k": np.arange(base, base + n, dtype=np.int64),
            "grp": np.array(["X", "Y"])[r.integers(0, 2, n)],
            "v": r.uniform(0, 10, n).round(3),
        })
        p = os.path.join(root, "data", name)
        pq.write_table(t, p)
        return p, t

    f1, t1 = write_data("f1.parquet", 500, 0)
    f2, t2 = write_data("f2.parquet", 400, 500)
    f3, t3 = write_data("f3.parquet", 300, 900)

    def manifest(name, entries):
        p = os.path.join(root, "metadata", name)
        recs = [
            {"status": st, "snapshot_id": sid,
             "data_file": {"file_path": f"{loc}/data/{os.path.basename(f)}",
                           "file_format": "PARQUET",
                           "record_count": 0, "file_size_in_bytes": 0}}
            for st, sid, f in entries
        ]
        write_container(p, MANIFEST_SCHEMA, recs)
        return p

    def manifest_list(name, manifests):
        p = os.path.join(root, "metadata", name)
        recs = [{"manifest_path": f"{loc}/metadata/{os.path.basename(m)}",
                 "manifest_length": os.path.getsize(m),
                 "partition_spec_id": 0} for m in manifests]
        write_container(p, MANIFEST_LIST_SCHEMA, recs)
        return p

    m1 = manifest("m1.avro", [(1, 1, f1), (1, 1, f2)])
    ml1 = manifest_list("snap-1.avro", [m1])
    # snapshot 2: f1 deleted, f3 added (f2 carried forward as EXISTING=0)
    m2 = manifest("m2.avro", [(2, 2, f1), (0, 1, f2), (1, 2, f3)])
    ml2 = manifest_list("snap-2.avro", [m2])

    meta = {
        "format-version": 2,
        "location": loc,
        "current-snapshot-id": 2,
        "snapshots": [
            {"snapshot-id": 1, "manifest-list": f"{loc}/metadata/snap-1.avro"},
            {"snapshot-id": 2, "manifest-list": f"{loc}/metadata/snap-2.avro"},
        ],
    }
    with open(os.path.join(root, "metadata", "v1.metadata.json"), "w") as f:
        json.dump(meta, f)
    with open(os.path.join(root, "metadata", "version-hint.text"), "w") as f:
        f.write("1")
    return {"t1": t1, "t2": t2, "t3": t3}


class TestIceberg:
    def test_data_files_current_and_time_travel(self, tmp_path):
        root = str(tmp_path / "tbl")
        build_iceberg_table(root)
        tbl = IcebergTable(root)
        cur = [os.path.basename(p) for p in tbl.data_files()]
        assert cur == ["f2.parquet", "f3.parquet"]  # f1 deleted in s2
        old = [os.path.basename(p) for p in tbl.data_files(snapshot_id=1)]
        assert old == ["f1.parquet", "f2.parquet"]

    def test_read_iceberg_query(self, tmp_path):
        root = str(tmp_path / "tbl")
        ts = build_iceberg_table(root)
        ctx = QuokkaContext()
        got = (
            ctx.read_iceberg(root)
            .filter_sql("v < 8")
            .groupby("grp")
            .agg_sql("sum(v) as sv, count(*) as n")
            .collect()
            .sort_values("grp").reset_index(drop=True)
        )
        pdf = pa.concat_tables([ts["t2"], ts["t3"]]).to_pandas()
        pdf = pdf[pdf.v < 8]
        exp = pdf.groupby("grp").agg(sv=("v", "sum"), n=("v", "size")).reset_index()
        np.testing.assert_allclose(got.sv.to_numpy(), exp.sv.to_numpy(), rtol=1e-9)
        assert got.n.tolist() == exp.n.tolist()

    def test_read_iceberg_time_travel(self, tmp_path):
        root = str(tmp_path / "tbl")
        ts = build_iceberg_table(root)
        ctx = QuokkaContext()
        got = ctx.read_iceberg(root, snapshot_id=1).collect()
        exp = pa.concat_tables([ts["t1"], ts["t2"]]).to_pandas()
        assert len(got) == len(exp)
        assert sorted(got.k.tolist()) == sorted(exp.k.tolist())

    def test_relocated_table_reroots_paths(self, tmp_path):
        """Metadata written under another root (location mismatch) still
        resolves because paths under `location` re-root onto the table dir."""
        import shutil

        root = str(tmp_path / "orig")
        build_iceberg_table(root)
        moved = str(tmp_path / "moved")
        shutil.move(root, moved)
        tbl = IcebergTable(moved)
        files = tbl.data_files()
        assert all(p.startswith(moved) for p in files)
        assert all(os.path.exists(p) for p in files)

    def test_missing_snapshot_raises(self, tmp_path):
        root = str(tmp_path / "tbl")
        build_iceberg_table(root)
        with pytest.raises(ValueError, match="snapshot 99"):
            IcebergTable(root).data_files(snapshot_id=99)

    def test_delete_manifest_rejected(self, tmp_path):
        """A v2 manifest-list entry with content=1 (delete manifest) must
        raise, not be scanned as data."""
        root = str(tmp_path / "tbl")
        build_iceberg_table(root)
        loc = f"file://{root}"
        schema = {
            "type": "record", "name": "manifest_file", "fields": [
                {"name": "manifest_path", "type": "string"},
                {"name": "manifest_length", "type": "long"},
                {"name": "partition_spec_id", "type": "int"},
                {"name": "content", "type": "int"},
            ],
        }
        write_container(
            os.path.join(root, "metadata", "snap-2.avro"), schema,
            [{"manifest_path": f"{loc}/metadata/m2.avro",
              "manifest_length": 1, "partition_spec_id": 0, "content": 1}],
        )
        with pytest.raises(ValueError, match="delete"):
            IcebergTable(root).data_files()

    def test_delete_data_file_rejected(self, tmp_path):
        """A data_file struct with content!=0 (position/equality deletes)
        must raise, not be appended to the scan list."""
        root = str(tmp_path / "tbl")
        build_iceberg_table(root)
        loc = f"file://{root}"
        schema = {
            "type": "record", "name": "manifest_entry", "fields": [
                {"name": "status", "type": "int"},
                {"name": "snapshot_id", "type": ["null", "long"]},
                {"name": "data_file", "type": {
                    "type": "record", "name": "r2", "fields": [
                        {"name": "content", "type": "int"},
                        {"name": "file_path", "type": "string"},
                        {"name": "file_format", "type": "string"},
                        {"name": "record_count", "type": "long"},
                        {"name": "file_size_in_bytes", "type": "long"},
                    ]}},
            ],
        }
        write_container(
            os.path.join(root, "metadata", "m2.avro"), schema,
            [{"status": 1, "snapshot_id": 2,
              "data_file": {"content": 1,
                            "file_path": f"{loc}/data/f1-deletes.parquet",
                            "file_format": "PARQUET",
                            "record_count": 0, "file_size_in_bytes": 0}}],
        )
        with pytest.raises(ValueError, match="delete files"):
            IcebergTable(root).data_files()
