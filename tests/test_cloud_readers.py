"""Object-store + REST readers (VERDICT r1 item 8): the S3 byte-range CSV /
row-group Parquet designs run over fsspec, driven here against file:// so the
exact cloud code path is tested without network.  REST pages come from a
local HTTP server."""

import json
import threading

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from quokka_tpu import QuokkaContext


@pytest.fixture(scope="module")
def data_dir(tmp_path_factory):
    root = tmp_path_factory.mktemp("cloud")
    r = np.random.default_rng(5)
    n = 20000
    df = pd.DataFrame({
        "k": r.integers(0, 100, n),
        "name": np.array(["aa", "bb", "cc"])[r.integers(0, 3, n)],
        "v": r.uniform(0, 10, n).round(4),
    })
    df.to_csv(root / "t.csv", index=False)
    pq.write_table(pa.Table.from_pandas(df), root / "t.parquet",
                   row_group_size=2048)
    return root, df


class TestObjectCSV:
    def test_byte_range_csv_matches(self, data_dir):
        root, df = data_dir
        ctx = QuokkaContext()
        # tiny stride -> many byte ranges; every row parsed exactly once
        from quokka_tpu import logical
        from quokka_tpu.dataset.cloud import InputObjectCSVDataset

        reader = InputObjectCSVDataset(f"file://{root}/t.csv", stride=64 << 10)
        s = ctx.new_stream(logical.SourceNode(reader, list(reader.schema)))
        got = s.collect()
        assert len(got) == len(df)
        np.testing.assert_allclose(
            np.sort(got.v.to_numpy(dtype=float)), np.sort(df.v.to_numpy())
        )
        got2 = (
            ctx.new_stream(logical.SourceNode(
                InputObjectCSVDataset(f"file://{root}/t.csv", stride=64 << 10),
                list(reader.schema)))
            .groupby("name").agg_sql("count(*) as n, sum(v) as sv").collect()
            .sort_values("name").reset_index(drop=True)
        )
        exp = df.groupby("name").v.agg(["size", "sum"]).reset_index()
        assert got2.n.tolist() == exp["size"].tolist()
        np.testing.assert_allclose(got2.sv.to_numpy(), exp["sum"].to_numpy(), rtol=1e-9)

    def test_url_routing_via_context(self, data_dir):
        root, df = data_dir
        got = QuokkaContext().read_csv(f"file://{root}/t.csv").collect()
        assert len(got) == len(df)


class TestObjectParquet:
    def test_row_groups_and_pruning(self, data_dir):
        root, df = data_dir
        ctx = QuokkaContext()
        got = (
            ctx.read_parquet(f"file://{root}/t.parquet")
            .filter_sql("k < 10")
            .groupby("k").agg_sql("count(*) as n")
            .collect().sort_values("k").reset_index(drop=True)
        )
        exp = df[df.k < 10].groupby("k").size().reset_index(name="n")
        assert got.k.tolist() == exp.k.tolist()
        assert got.n.tolist() == exp.n.tolist()


class TestRest:
    def test_paged_rest_reader(self, data_dir):
        import http.server

        pages = {
            "0": [{"t": 1, "price": 10.0}, {"t": 2, "price": 11.0}],
            "1": [{"t": 3, "price": 12.5}, {"t": 4, "price": 9.0}],
            "2": [{"t": 5, "price": 13.0}],
        }

        class H(http.server.BaseHTTPRequestHandler):
            def do_GET(self):
                from urllib.parse import parse_qs, urlparse

                q = parse_qs(urlparse(self.path).query)
                body = json.dumps(
                    {"data": pages.get(q.get("page", ["0"])[0], [])}
                ).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), H)
        th = threading.Thread(target=srv.serve_forever, daemon=True)
        th.start()
        try:
            url = f"http://127.0.0.1:{srv.server_address[1]}/ticks"
            ctx = QuokkaContext()
            got = (
                ctx.read_rest(
                    [(url, {"page": str(i)}) for i in range(3)],
                    record_path="data",
                )
                .agg_sql("sum(price) as s, count(*) as n")
                .collect()
            )
            assert got.n[0] == 5
            np.testing.assert_allclose(got.s[0], 55.5)
        finally:
            srv.shutdown()

    def test_rest_post_reader(self, data_dir):
        import http.server

        class H(http.server.BaseHTTPRequestHandler):
            def do_POST(self):
                n = int(self.headers.get("Content-Length", "0"))
                req = json.loads(self.rfile.read(n) or b"{}")
                page = req.get("page", 0)
                rows = [
                    {"t": page * 10 + i, "v": float(page * 10 + i)}
                    for i in range(req.get("limit", 2))
                ]
                body = json.dumps({"rows": rows}).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), H)
        th = threading.Thread(target=srv.serve_forever, daemon=True)
        th.start()
        try:
            url = f"http://127.0.0.1:{srv.server_address[1]}/query"
            ctx = QuokkaContext()
            got = (
                ctx.read_rest(
                    [(url, {"page": p, "limit": 3}) for p in range(2)],
                    record_path="rows",
                    method="post",
                )
                .agg_sql("count(*) as n, sum(v) as s")
                .collect()
            )
            assert got.n[0] == 6
            # pages 0 and 1: values 0,1,2 and 10,11,12
            np.testing.assert_allclose(got.s[0], 0 + 1 + 2 + 10 + 11 + 12)
        finally:
            srv.shutdown()

    def test_rest_rejects_unknown_method(self):
        from quokka_tpu.dataset.cloud import InputRestDataset

        with pytest.raises(ValueError, match="method"):
            InputRestDataset([("http://x", None)], method="delete")


class TestWholeFiles:
    def test_disk_directory_as_rows(self, tmp_path):
        d = tmp_path / "blobs"
        d.mkdir()
        payloads = {}
        for i in range(7):
            p = d / f"img_{i}.bin"
            payloads[str(p)] = bytes([i]) * (10 + i)
            p.write_bytes(payloads[str(p)])
        ctx = QuokkaContext(io_channels=3)
        got = ctx.read_files(str(d)).collect()
        assert sorted(got.filename) == sorted(payloads)
        by_name = dict(zip(got.filename, got.object))
        for name, blob in payloads.items():
            assert bytes(by_name[name]) == blob

    def test_glob_and_batching(self, tmp_path):
        d = tmp_path / "docs"
        d.mkdir()
        for i in range(5):
            (d / f"doc{i}.txt").write_bytes(b"x" * i)
        (d / "skip.dat").write_bytes(b"nope")
        ctx = QuokkaContext()
        got = ctx.read_files(str(d / "*.txt"), files_per_batch=2).collect()
        assert len(got) == 5
        assert all(f.endswith(".txt") for f in got.filename)

    def test_missing_path_raises(self, tmp_path):
        from quokka_tpu.dataset.cloud import InputFilesDataset

        with pytest.raises(FileNotFoundError):
            InputFilesDataset(str(tmp_path / "nope" / "*")).get_own_state(2)

    def test_binary_roundtrip_through_device(self):
        # blobs dictionary-encode (codes on device, bytes on host) and come
        # back as pa.binary, not stringified
        import pyarrow as pa

        from quokka_tpu.ops import bridge

        t = pa.table({
            "name": ["a", "b", "c", "a"],
            "blob": pa.array([b"\x00\x01", b"xyz", None, b"\x00\x01"], pa.binary()),
        })
        b = bridge.arrow_to_device(t)
        back = bridge.device_to_arrow(b)
        assert back.schema.field("blob").type == pa.binary()
        assert back.column("blob").to_pylist() == [b"\x00\x01", b"xyz", None, b"\x00\x01"]


class TestLance:
    def test_read_lance_absent_names_substitute(self):
        try:
            import lance  # noqa: F401

            pytest.skip("lance present: fallback path not reachable")
        except ImportError:
            pass
        ctx = QuokkaContext()
        with pytest.raises(ImportError, match="IVF sidecar"):
            ctx.read_lance("/tmp/nonexistent.lance")


class TestAnnPushdown:
    """IVF sidecar + push_ann (the Lance vector-index role, VERDICT item 8)."""

    def test_index_prunes_and_recall_holds(self, tmp_path):
        r = np.random.default_rng(0)
        # clustered vectors so IVF cells align with row groups poorly enough
        # to be honest but well enough to prune
        n, dim = 8000, 16
        centers = r.normal(size=(8, dim)) * 5
        assign = r.integers(0, 8, n)
        vecs = centers[assign] + r.normal(size=(n, dim)) * 0.3
        t = pa.table({
            "id": np.arange(n, dtype=np.int64),
            "vec": pa.FixedSizeListArray.from_arrays(
                pa.array(vecs.astype(np.float32).reshape(-1)), dim
            ),
        })
        path = str(tmp_path / "vecs.parquet")
        pq.write_table(t, path, row_group_size=512)

        from quokka_tpu.dataset.vector import build_vector_index, prune_row_groups
        build_vector_index(path, "vec", n_cells=16, iters=5)

        queries = centers[:3] + r.normal(size=(3, dim)) * 0.1
        keep = prune_row_groups(path, queries, nprobe=2)
        assert keep is not None and 0 < len(keep) <= 16

        ctx = QuokkaContext()
        exact = (
            ctx.read_parquet(path)
            .nearest_neighbors(queries, "vec", k=5, payload=["id"])
            .collect()
        )
        approx = (
            ctx.read_parquet(path)
            .nearest_neighbors(queries, "vec", k=5, payload=["id"],
                               approximate=True, nprobe=4)
            .collect()
        )
        assert len(approx) == len(exact) == 15
        # clustered data + generous nprobe: recall should be near-perfect
        overlap = len(set(map(tuple, approx[["query_idx", "id"]].to_numpy()))
                      & set(map(tuple, exact[["query_idx", "id"]].to_numpy())))
        assert overlap >= 12, overlap

    def test_ann_prune_does_not_leak_to_exact_queries(self, tmp_path):
        r = np.random.default_rng(1)
        n, dim = 2000, 8
        vecs = r.normal(size=(n, dim)).astype(np.float32)
        t = pa.table({
            "id": np.arange(n, dtype=np.int64),
            "vec": pa.FixedSizeListArray.from_arrays(pa.array(vecs.reshape(-1)), dim),
        })
        path = str(tmp_path / "v.parquet")
        pq.write_table(t, path, row_group_size=256)
        from quokka_tpu.dataset.vector import build_vector_index
        build_vector_index(path, "vec", n_cells=8, iters=3)
        q = vecs[:2]
        ctx = QuokkaContext()
        src = ctx.read_parquet(path)
        _ = src.nearest_neighbors(q, "vec", 3, payload=["id"],
                                  approximate=True, nprobe=1).collect()
        # the SAME source re-queried exactly must scan everything again
        exact = src.nearest_neighbors(q, "vec", 3, payload=["id"]).collect()
        import jax.numpy as jnp
        xn = vecs / np.linalg.norm(vecs, axis=1, keepdims=True)
        qn = q / np.linalg.norm(q, axis=1, keepdims=True)
        sims = qn @ xn.T
        for qi in range(2):
            top = set(np.argsort(-sims[qi])[:3].tolist())
            got = set(exact[exact.query_idx == qi].id.tolist())
            assert got == top


class TestTornRows:
    def test_row_longer_than_stride(self, tmp_path):
        # a row spanning MULTIPLE byte ranges must be parsed exactly once,
        # by the range owning its first byte
        big = "x" * 5000
        lines = ["a,b", f"1,{big}", "2,yy", f"3,{'z' * 4000}", "4,w"]
        p = tmp_path / "wide.csv"
        p.write_text("\n".join(lines) + "\n")
        from quokka_tpu import logical
        from quokka_tpu.dataset.cloud import InputObjectCSVDataset

        reader = InputObjectCSVDataset(f"file://{p}", stride=1000)
        ctx = QuokkaContext()
        got = (
            ctx.new_stream(logical.SourceNode(reader, list(reader.schema)))
            .collect()
            .sort_values("a")
            .reset_index(drop=True)
        )
        assert got.a.tolist() == [1, 2, 3, 4]
        assert got.b.tolist() == [big, "yy", "z" * 4000, "w"]

    def test_type_pinning_across_ranges(self, tmp_path):
        # numeric-looking prefix + text later: types must not flip per range
        rows = [f"{i},{i}" for i in range(3000)] + ["9999,not_a_number"]
        p = tmp_path / "mix.csv"
        p.write_text("a,b\n" + "\n".join(rows) + "\n")
        from quokka_tpu import logical
        from quokka_tpu.dataset.cloud import InputObjectCSVDataset

        reader = InputObjectCSVDataset(f"file://{p}", stride=4 << 10)
        ctx = QuokkaContext()
        got = ctx.new_stream(
            logical.SourceNode(reader, list(reader.schema))
        ).collect()
        assert len(got) == 3001
        assert "not_a_number" in set(got.b.astype(str))
