"""Prometheus exporter + /metrics //status sidecar: text-format rendering
(escaping, label families, histogram series), histogram quantiles, and an
e2e scrape of a live 2-query QueryService run (ISSUE 5)."""

import json
import re
import time
import urllib.error
import urllib.request

import numpy as np
import pyarrow as pa
import pytest

from quokka_tpu import QuokkaContext
from quokka_tpu.obs import export
from quokka_tpu.obs.metrics import Registry
from quokka_tpu.service import QueryService

# one Prometheus text-format sample line: name{labels} value
_SAMPLE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? [0-9eE.+-]+$|^# (TYPE|HELP) ")


def _valid_exposition(text):
    for line in text.strip().splitlines():
        assert _SAMPLE.match(line) or line.startswith("# "), line
    return True


class TestRender:
    def test_label_escaping(self):
        r = Registry()
        r.counter('cache.plan_hit.we"ird\\q\nid').inc(2)
        text = export.render(r)
        assert (r'quokka_cache_plan_hit_total{query="we\"ird\\q\nid"} 2'
                in text)
        assert _valid_exposition(text)

    def test_counter_gauge_histogram_families(self):
        r = Registry()
        r.counter("integrity.corrupt").inc()
        r.counter("rpc.tget").inc(5)
        r.gauge("pool.size").set(2)
        h = r.histogram("task.latency_s")
        for v in (0.001, 0.02, 3.0):
            h.observe(v)
        text = export.render(r)
        assert "# TYPE quokka_integrity_corrupt_total counter" in text
        assert 'quokka_rpc_calls_total{method="tget"} 5' in text
        assert "quokka_pool_size 2" in text
        # histogram: cumulative buckets, +Inf, sum and count.  The
        # process-wide aggregate renders as its OWN family (every dispatch
        # also lands in the per-query labeled family; sharing one family
        # would double-count under sum()-style PromQL)
        assert "# TYPE quokka_task_latency_all_seconds histogram" in text
        assert 'quokka_task_latency_all_seconds_bucket{le="+Inf"} 3' in text
        assert "quokka_task_latency_all_seconds_count 3" in text
        m = re.search(r"quokka_task_latency_all_seconds_sum ([\d.]+)", text)
        assert m and float(m.group(1)) == pytest.approx(3.021)
        # cumulative monotonicity across the series
        buckets = [int(x) for x in re.findall(
            r'quokka_task_latency_all_seconds_bucket\{le="[^"]+"\} (\d+)',
            text)]
        assert buckets == sorted(buckets) and buckets[-1] == 3
        assert _valid_exposition(text)

    def test_aggregate_and_per_query_families_are_distinct(self):
        """One observation into both the aggregate and a per-query series
        must NOT appear twice in one family (scrape-side sum() would
        double-count the task rate)."""
        r = Registry()
        r.histogram("task.latency_s").observe(0.01)
        r.histogram("task.latency_s.q1").observe(0.01)
        r.counter("cache.plan_hit").inc()
        r.counter("cache.plan_hit.q1").inc()
        text = export.render(r)
        assert "quokka_task_latency_seconds_count 1" not in text
        assert ('quokka_task_latency_seconds_count{query="q1"} 1'
                in text)
        assert "quokka_task_latency_all_seconds_count 1" in text
        assert "quokka_cache_plan_hit_all_total 1" in text
        assert 'quokka_cache_plan_hit_total{query="q1"} 1' in text
        assert "quokka_cache_plan_hit_total 1\n" not in text

    def test_mem_families_render_with_labels(self):
        """Memory-plane gauges: per-query and per-site twins render as
        labeled families (escaping included); the aggregates keep their own
        _all names so sum() over the labeled family never double-counts."""
        r = Registry()
        r.gauge("mem.live_bytes").set(1024)
        r.gauge('mem.live_bytes.q"1').set(512)
        r.gauge('mem.peak_bytes.q"1').set(2048)
        r.gauge('mem.spill_resident_bytes.q"1').set(128)
        r.gauge("mem.peak_bytes").set(4096)
        r.gauge("mem.spill_resident_bytes").set(256)
        r.gauge("mem.site_bytes.shuffle").set(640)
        text = export.render(r)
        assert "quokka_mem_live_bytes_all 1024" in text
        assert "quokka_mem_peak_bytes_all 4096" in text
        assert "quokka_mem_spill_resident_bytes_all 256" in text
        assert 'quokka_mem_live_bytes{query="q\\"1"} 512' in text
        assert 'quokka_mem_peak_bytes{query="q\\"1"} 2048' in text
        assert ('quokka_mem_spill_resident_bytes{query="q\\"1"} 128'
                in text)
        assert 'quokka_mem_site_bytes{site="shuffle"} 640' in text
        # the aggregate never renders bare under the labeled family name
        assert "quokka_mem_live_bytes 1024" not in text
        assert _valid_exposition(text)

    def test_per_query_histogram_renders_as_label(self):
        r = Registry()
        r.histogram("task.latency_s.qfoo").observe(0.01)
        text = export.render(r)
        assert ('quokka_task_latency_seconds_count{query="qfoo"} 1'
                in text)

    def test_extra_gauges(self):
        text = export.render(Registry(),
                             extra_gauges={"obs_dropped_events": 7})
        assert "quokka_obs_dropped_events 7" in text


class TestHistogramQuantiles:
    def test_quantiles_track_observations(self):
        r = Registry()
        h = r.histogram("task.latency_s")
        assert h.quantile(0.5) is None  # empty
        for _ in range(90):
            h.observe(0.003)
        for _ in range(10):
            h.observe(1.8)
        st = h.stats()
        assert st["count"] == 100
        assert 0.0025 <= st["p50"] <= 0.005
        assert 1.0 <= st["p95"] <= 2.5  # rank 95 falls in the tail mass
        assert st["sum"] == pytest.approx(90 * 0.003 + 10 * 1.8)

    def test_overflow_bucket_reports_last_bound(self):
        r = Registry()
        h = r.histogram("x_s", buckets=(0.1, 1.0))
        h.observe(50.0)
        assert h.quantile(0.5) == 1.0

    def test_conflicting_bucket_request_raises(self):
        r = Registry()
        r.histogram("x_s", buckets=(0.1, 1.0))
        with pytest.raises(ValueError, match="already exists"):
            r.histogram("x_s", buckets=(0.5, 5.0))
        assert r.histogram("x_s").bounds == (0.1, 1.0)  # no-buckets reuse ok


def _scrape(url, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.status, resp.headers.get("Content-Type"), \
            resp.read().decode()


class TestHttpSidecar:
    def test_metrics_status_and_404(self):
        server = export.MetricsServer(port=0)
        try:
            code, ctype, text = _scrape(server.url("/metrics"))
            assert code == 200 and ctype.startswith("text/plain")
            assert "quokka_obs_dropped_events" in text
            code, ctype, body = _scrape(server.url("/status"))
            assert code == 200 and ctype == "application/json"
            status = json.loads(body)
            assert status["obs"]["recorder_enabled"] in (True, False)
            assert "service" not in status  # none attached
            with pytest.raises(urllib.error.HTTPError) as ei:
                _scrape(server.url("/nope"))
            assert ei.value.code == 404
        finally:
            server.close()

    def test_status_format_twins(self):
        """/status?format=json is the explicit machine spelling of the
        default JSON payload; ?format=text renders the same dict for
        humans (ISSUE 17 satellite)."""
        server = export.MetricsServer(port=0)
        try:
            _, ctype, body = _scrape(server.url("/status?format=json"))
            assert ctype == "application/json"
            explicit = json.loads(body)
            _, _, default_body = _scrape(server.url("/status"))
            assert set(explicit) == set(json.loads(default_body))
            assert "obs" in explicit and "pid" in explicit
            code, ctype, text = _scrape(server.url("/status?format=text"))
            assert code == 200 and ctype.startswith("text/plain")
            assert text.startswith("quokka pid=")
            assert "health=" in text
        finally:
            server.close()

    def test_history_and_health_endpoints(self):
        from quokka_tpu.obs import alerts, history

        server = export.MetricsServer(port=0)
        try:
            history.RING.record()
            history.RING.record()
            code, ctype, body = _scrape(server.url("/history"))
            assert code == 200 and ctype == "application/json"
            hist = json.loads(body)
            assert {"interval_s", "depth", "samples", "rates"} <= set(hist)
            assert len(hist["samples"]) >= 2
            assert {"t", "counters", "gauges", "histograms"} <= set(
                hist["samples"][-1])
            code, ctype, body = _scrape(server.url("/health"))
            assert code == 200 and ctype == "application/json"
            health = json.loads(body)
            assert health["status"] in ("ok", "degraded", "critical")
            assert isinstance(health["firing"], list)
            assert health["status"] == alerts.ENGINE.health()["status"]
        finally:
            server.close()

    def test_start_from_env(self, monkeypatch):
        monkeypatch.delenv("QK_METRICS_PORT", raising=False)
        assert export.start_from_env() is None
        monkeypatch.setenv("QK_METRICS_PORT", "0")
        server = export.start_from_env()
        try:
            assert server is not None and server.port > 0
        finally:
            server.close()


def _slow_query(ctx, n=40_000, delay_s=0.02):
    from quokka_tpu.dataset.readers import InputArrowDataset

    r = np.random.default_rng(1)
    table = pa.table({"k": r.integers(0, 16, n).astype(np.int64),
                      "v": r.integers(0, 1000, n).astype(np.int64)})

    class Slow(InputArrowDataset):
        def execute(self, channel, lineage):
            time.sleep(delay_s)
            return super().execute(channel, lineage)

    return (ctx.read_dataset(Slow(table, batch_rows=2048))
            .groupby("k").agg_sql("sum(v) as sv, count(*) as n"))


class TestLiveServiceScrape:
    def test_scrape_during_two_query_run(self, monkeypatch):
        """ISSUE 5 acceptance: curl :$QK_METRICS_PORT/metrics during a live
        2-query service run returns valid Prometheus text exposition
        including per-query histograms; /status names the live queries."""
        monkeypatch.setenv("QK_METRICS_PORT", "0")
        with QueryService(pool_size=2) as svc:
            assert svc.metrics_server is not None
            h1 = svc.submit(_slow_query(QuokkaContext()))
            h2 = svc.submit(_slow_query(QuokkaContext()))
            qids = {h1.query_id, h2.query_id}
            # poll until both queries are live AND have dispatched tasks
            deadline = time.time() + 30
            status = text = None
            while time.time() < deadline:
                _, _, body = _scrape(svc.metrics_server.url("/status"))
                status = json.loads(body)
                sess = status["service"]["sessions"]
                if (set(sess) == qids
                        and all(s["status"] == "running"
                                and s["tasks"] > 0 for s in sess.values())):
                    _, ctype, text = _scrape(
                        svc.metrics_server.url("/metrics"))
                    assert ctype.startswith("text/plain")
                    break
                time.sleep(0.01)
            assert text is not None, f"queries never ran concurrently: " \
                                     f"{status}"
            assert _valid_exposition(text)
            for qid in qids:  # per-query task-latency histograms, live
                assert (f'quokka_task_latency_seconds_count'
                        f'{{query="{qid}"}}' in text), text[:800]
            sess = status["service"]["sessions"]
            for qid in qids:
                assert sess[qid]["task_p50_s"] is None or \
                    sess[qid]["task_p50_s"] > 0
            assert "admission" in status["service"]
            assert status["service"]["workers_alive"] == 2
            for h in (h1, h2):
                assert h.to_df(timeout=300) is not None
            # the per-query latency snapshot survives the namespace GC
            lat = h1.latency_stats()
            assert lat["count"] > 0 and lat["p50"] > 0
        # sidecar stops with the service: the socket must refuse
        with pytest.raises((urllib.error.URLError, ConnectionError,
                            OSError)):
            _scrape(svc.metrics_server.url("/metrics"), timeout=2)
