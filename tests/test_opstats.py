"""Operator-statistics ledger (obs/opstats.py) + EXPLAIN rendering
(obs/explain.py): unit coverage over a synthetic plan, no engine runs.
The end-to-end path (engine choke points, zero added syncs, admission
feedback) is proven by `make explain-smoke`."""

import json
import os
import threading

import pytest

from quokka_tpu import obs
from quokka_tpu.obs import explain
from quokka_tpu.obs import opstats
from quokka_tpu.obs.opstats import OpStats


class _Reader:
    def __init__(self, hint):
        self._hint = hint

    def size_hint(self):
        return self._hint


class _Actor:
    def __init__(self, kind, channels=2, targets=(), stage=0, reader=None):
        self.kind = kind
        self.channels = channels
        self.targets = {t: None for t in targets}
        self.stage = stage
        if reader is not None:
            self.reader = reader


class _Graph:
    """The minimal TaskGraph surface register_plan reads."""

    def __init__(self, qid, actors, plan_fp="fp-test"):
        self.query_id = qid
        self.actors = actors
        self.plan_fp = plan_fp


def _two_stage_graph(qid="qtest"):
    return _Graph(qid, {
        0: _Actor("input", channels=2, targets=(1,),
                  reader=_Reader(1 << 20)),
        1: _Actor("exec", channels=2, targets=(2,), stage=1),
        2: _Actor("exec", channels=1, stage=2),
    })


class _Dev:
    """Stands in for a device scalar: resolvable via int() like the async
    d2h copies the engine queues."""

    def __init__(self, n):
        self._n = n

    def __int__(self):
        return self._n


class _Valid:
    nbytes = 128


class _Batch:
    def __init__(self, nrows=None, nrows_dev=None, padded_len=0):
        self.nrows = nrows
        self.nrows_dev = nrows_dev
        self.padded_len = padded_len
        self.valid = _Valid()  # _batch_nbytes sums valid + column buffers
        self.columns = {}


@pytest.fixture
def ledger():
    s = OpStats()
    yield s
    s.reset()


def _feed(s, qid="qtest"):
    """A complete little run: 1000 scan rows (900 past the predicate),
    skewed exchange onto a1, aggregate down to 10 rows at a2."""
    s.register_plan(_two_stage_graph(qid))
    s.scan(qid, 0, 0, rows_raw=600, rows_out=500, nbytes=6000, padded=640)
    s.scan(qid, 0, 1, rows_raw=400, rows_out=400, nbytes=4000, padded=512)
    # every row lands on channel 0: max/mean = 2.0 on 2 channels, the
    # highest ratio a 2-channel edge can show — exactly at the threshold
    s.edge(qid, 0, 1, 0, 900)
    s.exec_in(qid, 1, 0, [_Batch(nrows=900, padded_len=1024)])
    s.exec_out(qid, 1, 0, 900)
    s.edge(qid, 1, 2, 0, 900)
    s.exec_in(qid, 2, 0, [_Batch(nrows=900, padded_len=1024)])
    s.exec_out(qid, 2, 0, 10)
    s.dispatch_time(qid, 1, 0, 0.3)
    s.dispatch_time(qid, 2, 0, 0.1)


class TestLedger:
    def test_snapshot_reconciles_and_flags_skew(self, ledger):
        _feed(ledger)
        snap = ledger.snapshot("qtest")
        ops = {o["actor"]: o for o in snap["operators"]}
        assert ops[0]["rows_in"] == 1000 and ops[0]["rows_out"] == 900
        assert ops[0]["selectivity"] == 0.9
        assert ops[0]["size_hint_bytes"] == 1 << 20
        assert ops[1]["rows_in"] == 900 and ops[2]["rows_out"] == 10
        # pad_waste: 900 live rows in 1024 padded slots on a1
        assert ops[1]["pad_waste"] == round(1 - 900 / 1024, 4)
        edges = {e["edge"]: e for e in snap["edges"]}
        e01 = edges["a0->a1"]
        assert e01["channel_rows"] == [900, 0]
        assert e01["skew_ratio"] == 2.0
        assert e01["skewed"] is True  # default threshold 2.0
        assert edges["a1->a2"]["skewed"] is False  # single channel
        assert snap["rows_unknown"] == 0
        # a1 carried 0.3s of 0.4s total
        assert snap["top_operators"][0]["actor"] == 1
        assert ops[1]["time_share"] == 0.75

    def test_unregistered_query_records_nothing(self, ledger):
        ledger.scan("ghost", 0, 0, rows_raw=5, rows_out=5, nbytes=1,
                    padded=8)
        ledger.edge("ghost", 0, 1, 0, 5)
        assert ledger.snapshot("ghost") is None
        assert ledger.live_queries() == []

    def test_device_scalars_resolve_at_flush_cadence(self, ledger):
        qid = "qdev"
        ledger.register_plan(_two_stage_graph(qid))
        ledger.exec_in(qid, 1, 0, [_Batch(nrows_dev=_Dev(70),
                                          padded_len=128)])
        ledger.exec_out(qid, 1, 0, _Dev(30))
        ledger.edge(qid, 0, 1, 0, _Dev(70))
        snap = ledger.snapshot(qid)  # snapshot() drains pending first
        op1 = next(o for o in snap["operators"] if o["actor"] == 1)
        assert op1["rows_in"] == 70 and op1["rows_out"] == 30
        assert snap["edges"][0]["rows_total"] == 70
        assert op1["rows_unknown"] == 0

    def test_unresolvable_rows_counted_never_synced(self, ledger):
        qid = "qunk"
        ledger.register_plan(_two_stage_graph(qid))
        ledger.exec_in(qid, 1, 0, [_Batch()])  # no nrows, no nrows_dev
        snap = ledger.snapshot(qid)
        assert snap["rows_unknown"] == 1

    def test_note_attributes_through_current_op(self, ledger):
        qid = "qnote"
        ledger.register_plan(_two_stage_graph(qid))
        orig = opstats.OPSTATS
        opstats.OPSTATS = ledger  # note() routes via the module singleton
        try:
            with ledger.current_op(qid, 1, 0):
                opstats.note(join_build_rows=40)
                opstats.note(join_build_rows=2)
            opstats.note(join_build_rows=999)  # outside a dispatch: no-op
        finally:
            opstats.OPSTATS = orig
        snap = ledger.snapshot(qid)
        op1 = next(o for o in snap["operators"] if o["actor"] == 1)
        assert op1["join_build_rows"] == 42

    def test_gc_drops_state_keeps_last_snapshot(self, ledger):
        _feed(ledger)
        snap = ledger.on_query_gc("qtest", plan_fp=None)
        assert snap and snap["query_id"] == "qtest"
        assert ledger.live_queries() == []
        # straggler reports after GC never resurrect the query
        ledger.scan("qtest", 0, 0, rows_raw=5, rows_out=5, nbytes=1,
                    padded=8)
        assert ledger.last_finished()["operators"] == snap["operators"]
        # per-query gauge twins were removed from the registry
        reg = obs.REGISTRY.snapshot()
        assert not any(k.startswith("opstats.rows_in.qtest") for k in reg)

    def test_top_operator_line(self, ledger):
        _feed(ledger)
        line = ledger.top_operator("qtest")
        assert line and line.startswith("exec(a1)") and "rows=900" in line


class TestCardinalityProfile:
    def test_roundtrip_and_max_merge(self, ledger, tmp_path, monkeypatch):
        monkeypatch.setenv("QK_CARDPROFILE_DIR", str(tmp_path))
        _feed(ledger)
        snap = ledger.on_query_gc("qtest", plan_fp="fp-test")
        assert opstats.measured_source_bytes("fp-test") == \
            snap["operators"][0]["bytes_out"] == 10000
        assert opstats.measured_calib_rows() == 900
        assert opstats.measured_source_bytes("fp-other") is None
        # a smaller rerun max-merges: measured figures never shrink
        s2 = OpStats()
        s2.register_plan(_two_stage_graph("q2"))
        s2.scan("q2", 0, 0, rows_raw=10, rows_out=10, nbytes=100, padded=16)
        s2.on_query_gc("q2", plan_fp="fp-test")
        assert opstats.measured_source_bytes("fp-test") == 10000
        path = os.path.join(
            str(tmp_path), os.listdir(tmp_path)[0])
        prof = json.load(open(path))
        assert prof["plans"]["fp-test"]["runs"] == 2

    def test_corrupt_or_foreign_profile_ignored(self, tmp_path, monkeypatch):
        monkeypatch.setenv("QK_CARDPROFILE_DIR", str(tmp_path))
        path = opstats._profile_path()
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as f:
            f.write("{not json")
        assert opstats.measured_source_bytes("fp-test") is None
        with open(path, "w") as f:
            json.dump({"version": 1, "fingerprint": "other-backend",
                       "plans": {"fp-test": {"source_bytes": 7}}}, f)
        assert opstats.measured_source_bytes("fp-test") is None

    def test_disabled_dir_skips_persist_and_load(self, ledger, monkeypatch):
        monkeypatch.setenv("QK_CARDPROFILE_DIR", "")
        _feed(ledger)
        ledger.on_query_gc("qtest", plan_fp="fp-test")
        assert opstats.measured_source_bytes("fp-test") is None
        assert opstats.measured_calib_rows() is None


class TestExplainRendering:
    def test_render_and_detail(self, ledger):
        _feed(ledger)
        snap = ledger.snapshot("qtest")
        text = explain.render(snap)
        assert "EXPLAIN ANALYZE qtest" in text
        assert "skew report" in text and "** SKEWED **" in text
        assert "top operators by dispatch time:" in text
        det = explain.operators_detail(snap)
        assert len(det["operators"]) == 3
        assert det["skew"][0]["ratio"] == snap["edges"][0]["skew_ratio"]
        assert det["rows_unknown"] == 0
        assert explain.skew_flags(snap) == ["a0->a1"]

    def test_render_empty(self):
        assert "no operator statistics" in explain.render(None)
        assert explain.operators_detail(None) is None
        assert explain.skew_flags(None) == []


def test_concurrent_recording_is_consistent(ledger):
    """The hot-path mutators race from engine worker threads; totals must
    land exactly (single-lock discipline, no lost increments)."""
    qid = "qrace"
    ledger.register_plan(_two_stage_graph(qid))

    def pump(ch):
        for _ in range(200):
            ledger.scan(qid, 0, ch, rows_raw=3, rows_out=2, nbytes=10,
                        padded=4)
            ledger.edge(qid, 0, 1, ch, 2)
            ledger.exec_in(qid, 1, ch, [_Batch(nrows=2, padded_len=4)])

    ts = [threading.Thread(target=pump, args=(ch,)) for ch in (0, 1)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    snap = ledger.snapshot(qid)
    ops = {o["actor"]: o for o in snap["operators"]}
    assert ops[0]["rows_in"] == 1200 and ops[0]["rows_out"] == 800
    assert ops[1]["rows_in"] == 800
    assert snap["edges"][0]["rows_total"] == 800
    assert snap["edges"][0]["channel_rows"] == [400, 400]
