"""Null semantics (ADVICE r1): ingestion keeps nulls as sentinels, predicates
use SQL three-valued logic, aggregates skip nulls, left joins null-fill every
payload kind, and null join keys never match.  Oracles: pandas (which also
skips nulls in aggregations) and hand-computed expectations."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from quokka_tpu import QuokkaContext


def nullable_table():
    return pa.table(
        {
            "k": pa.array([1, 2, None, 4, 5, None, 2, 1], type=pa.int64()),
            "f": pa.array([1.0, None, 3.0, None, 5.0, 6.0, 7.0, 8.0]),
            "s": pa.array(["a", None, "c", "a", None, "b", "c", "a"]),
            "d": pa.array(
                [0, 10, None, 30, None, 50, 60, 70], type=pa.int32()
            ).cast(pa.date32()),
        }
    )


class TestIngestRoundtrip:
    def test_nulls_survive_collect(self):
        t = nullable_table()
        got = QuokkaContext().from_arrow(t).collect()
        exp = t.to_pandas()
        for c in ("k", "f", "s", "d"):
            np.testing.assert_array_equal(
                got[c].isna().to_numpy(), exp[c].isna().to_numpy(), err_msg=c
            )
        np.testing.assert_array_equal(
            got["k"].dropna().to_numpy(), exp["k"].dropna().to_numpy()
        )
        assert got["s"].dropna().tolist() == exp["s"].dropna().tolist()


class TestPredicates:
    def test_comparisons_exclude_nulls(self):
        t = nullable_table()
        ctx = QuokkaContext()
        for pred, oracle in [
            ("k > 1", lambda df: df[df.k > 1]),
            ("k < 5", lambda df: df[df.k < 5]),
            ("k != 2", lambda df: df[df.k.notna() & (df.k != 2)]),
            ("f <= 6.0", lambda df: df[df.f <= 6.0]),
            ("s = 'a'", lambda df: df[df.s == "a"]),
            ("s != 'a'", lambda df: df[df.s.notna() & (df.s != "a")]),
        ]:
            got = ctx.from_arrow(t).filter_sql(pred).collect()
            exp = oracle(t.to_pandas())
            assert len(got) == len(exp), pred

    def test_is_null(self):
        t = nullable_table()
        ctx = QuokkaContext()
        assert len(ctx.from_arrow(t).filter_sql("k is null").collect()) == 2
        assert len(ctx.from_arrow(t).filter_sql("k is not null").collect()) == 6
        assert len(ctx.from_arrow(t).filter_sql("s is null").collect()) == 2
        assert len(ctx.from_arrow(t).filter_sql("f is not null").collect()) == 6
        assert len(ctx.from_arrow(t).filter_sql("d is null").collect()) == 2


class TestAggregates:
    def test_null_skipping_aggs(self):
        t = nullable_table()
        got = (
            QuokkaContext()
            .from_arrow(t)
            .agg_sql(
                "count(*) as n, count(f) as nf, sum(f) as sf, avg(f) as af, "
                "min(f) as mf, max(f) as xf, count(k) as nk"
            )
            .collect()
        )
        df = t.to_pandas()
        assert got["n"][0] == len(df)
        assert got["nf"][0] == df.f.notna().sum()
        assert got["nk"][0] == df.k.notna().sum()
        np.testing.assert_allclose(got["sf"][0], df.f.sum())
        np.testing.assert_allclose(got["af"][0], df.f.mean())
        np.testing.assert_allclose(got["mf"][0], df.f.min())
        np.testing.assert_allclose(got["xf"][0], df.f.max())

    def test_grouped_null_key_groups_together(self):
        t = nullable_table()
        got = (
            QuokkaContext()
            .from_arrow(t)
            .groupby("k")
            .agg_sql("count(*) as n")
            .collect()
        )
        df = t.to_pandas()
        exp = df.groupby("k", dropna=False).size()
        assert len(got) == len(exp)
        # the null group exists and has the right count
        nulls = got[got.k.isna()]
        assert len(nulls) == 1 and nulls.n.iloc[0] == 2


class TestThreeValuedLogic:
    def test_not_over_null_comparison(self):
        t = nullable_table()
        ctx = QuokkaContext()
        # NOT (k = 2) with k null must exclude the null rows (SQL 3VL)
        got = ctx.from_arrow(t).filter_sql("not (k = 2)").collect()
        df = t.to_pandas()
        assert len(got) == len(df[df.k.notna() & (df.k != 2)])
        got2 = ctx.from_arrow(t).filter_sql("not (k > 2 and k < 5)").collect()
        exp2 = df[df.k.notna() & ~((df.k > 2) & (df.k < 5))]
        assert len(got2) == len(exp2)

    def test_in_and_not_in_exclude_nulls(self):
        t = nullable_table()
        ctx = QuokkaContext()
        df = t.to_pandas()
        got = ctx.from_arrow(t).filter_sql("k in (1, 2)").collect()
        assert len(got) == len(df[df.k.isin([1, 2])])
        got = ctx.from_arrow(t).filter_sql("k not in (1, 2)").collect()
        assert len(got) == len(df[df.k.notna() & ~df.k.isin([1, 2])])

    def test_sum_over_arithmetic_on_nullable(self):
        t = nullable_table()
        ctx = QuokkaContext()
        df = t.to_pandas()
        got = (
            ctx.from_arrow(t)
            .agg_sql("sum(k + 1) as s, count(k * 2) as c")
            .collect()
        )
        np.testing.assert_allclose(got["s"][0], (df.k + 1).sum())
        assert got["c"][0] == df.k.notna().sum()


class TestNullStrings:
    def test_groupby_nullable_string_key(self):
        t = nullable_table()
        got = (
            QuokkaContext()
            .from_arrow(t)
            .groupby("s")
            .agg_sql("count(*) as n")
            .collect()
        )
        df = t.to_pandas()
        exp = df.groupby("s", dropna=False).size().reset_index(name="n")
        assert len(got) == len(exp)
        nulls = got[got.s.isna()]
        assert len(nulls) == 1 and nulls.n.iloc[0] == 2
        m_got = {k: v for k, v in zip(got.s, got.n) if isinstance(k, str)}
        m_exp = {k: v for k, v in zip(exp.s, exp.n) if isinstance(k, str)}
        assert m_got == m_exp

    def test_not_like_excludes_nulls(self):
        t = nullable_table()
        ctx = QuokkaContext()
        df = t.to_pandas()
        got = ctx.from_arrow(t).filter_sql("s not like 'a%'").collect()
        # `.str.startswith` keeps None for null rows (object dtype), and
        # newer pandas refuses `~` over object blocks containing None —
        # fill the nulls (excluded by notna() anyway) before inverting
        startswith_a = df.s.str.startswith("a").fillna(False).astype(bool)
        exp = df[df.s.notna() & ~startswith_a]
        assert len(got) == len(exp)


class TestCoalesce:
    def test_coalesce_int_sentinel(self):
        t = nullable_table()
        got = (
            QuokkaContext()
            .from_arrow(t)
            .with_columns_sql("coalesce(k, 0) as k0, coalesce(f, -1.0) as f0")
            .collect()
        )
        df = t.to_pandas()
        np.testing.assert_array_equal(
            got.k0.to_numpy(dtype=float), df.k.fillna(0).to_numpy(dtype=float)
        )
        np.testing.assert_allclose(got.f0.to_numpy(), df.f.fillna(-1.0).to_numpy())


class TestJoins:
    def test_left_join_null_probe_key_general_path(self):
        # general (non-unique build) path: null-key probe rows must read as
        # unmatched despite dense_rank giving them an arbitrary rank
        left = pa.table(
            {"k": pa.array([1, None, 9], type=pa.int64()), "lv": [1.0, 2.0, 3.0]}
        )
        # duplicate build keys force hash_join_general; 9 is the largest key
        right = pa.table(
            {"k": pa.array([1, 1, 9], type=pa.int64()), "rv": [10.0, 11.0, 90.0]}
        )
        ctx = QuokkaContext()
        got = (
            ctx.from_arrow(left)
            .join(ctx.from_arrow(right), on="k", how="left")
            .collect()
        )
        nullrow = got[got.lv == 2.0]
        assert len(nullrow) == 1
        assert nullrow.rv.isna().all()
        assert len(got) == 4  # 2 matches for k=1, 1 for k=9, 1 null row
    def test_null_keys_never_match(self):
        left = pa.table({"k": pa.array([1, None, 2], type=pa.int64()),
                         "lv": [10.0, 20.0, 30.0]})
        right = pa.table({"k": pa.array([None, 1, 3], type=pa.int64()),
                          "rv": [100.0, 200.0, 300.0]})
        ctx = QuokkaContext()
        l = ctx.from_arrow(left)
        r = ctx.from_arrow(right)
        inner = l.join(r, on="k").collect()
        assert len(inner) == 1 and inner.rv.iloc[0] == 200.0
        semi = l.join(r, on="k", how="semi").collect()
        assert semi.lv.tolist() == [10.0]
        anti = l.join(r, on="k", how="anti").collect()
        assert sorted(anti.lv.tolist()) == [20.0, 30.0]

    def test_left_join_null_fills_all_kinds(self):
        left = pa.table({"k": pa.array([1, 2, 3], type=pa.int64()),
                         "lv": [1.0, 2.0, 3.0]})
        right = pa.table(
            {
                "k": pa.array([1], type=pa.int64()),
                "ri": pa.array([42], type=pa.int64()),
                "rf": pa.array([4.2]),
                "rs": pa.array(["hit"]),
                "rd": pa.array([100], type=pa.int32()).cast(pa.date32()),
            }
        )
        ctx = QuokkaContext()
        got = (
            ctx.from_arrow(left)
            .join(ctx.from_arrow(right), on="k", how="left")
            .collect()
            .sort_values("k")
            .reset_index(drop=True)
        )
        assert len(got) == 3
        matched = got[got.k == 1]
        assert matched.ri.iloc[0] == 42 and matched.rs.iloc[0] == "hit"
        unmatched = got[got.k != 1]
        for c in ("ri", "rf", "rs", "rd"):
            assert unmatched[c].isna().all(), c

    def test_left_join_empty_build_side(self):
        # VERDICT weak #7: left join where the build side filters to zero rows
        left = pa.table({"k": pa.array([1, 2], type=pa.int64()), "lv": [1.0, 2.0]})
        right = pa.table({"k": pa.array([9], type=pa.int64()), "rv": [9.0]})
        ctx = QuokkaContext()
        got = (
            ctx.from_arrow(left)
            .join(
                ctx.from_arrow(right).filter_sql("k < 0"), on="k", how="left"
            )
            .collect()
        )
        assert len(got) == 2
        assert got.rv.isna().all()
