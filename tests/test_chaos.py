"""Chaos plane + transient-failure hardening (quokka_tpu/chaos, runtime
integrity/retry): the corruption matrix (truncate/bit-flip x spill/ckpt)
must be DETECTED via checksum and recovered bit-exactly; RPC disconnects
must reconnect with backoff and dedup the retried request; remote
checkpoint saves must be atomic (tmp key + move + verify)."""

import os
import socket
import threading

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from quokka_tpu import QuokkaContext, obs
from quokka_tpu.chaos import CHAOS, ChaosConfig, ChaosSpecError
from quokka_tpu.dataset.readers import InputArrowDataset
from quokka_tpu.runtime import integrity
from quokka_tpu.runtime.errors import (
    CorruptArtifactError,
    RpcTransportError,
    TransientStoreError,
)


@pytest.fixture(autouse=True)
def _chaos_off():
    """Every test starts and ends with the chaos plane inert."""
    CHAOS.disable()
    yield
    CHAOS.disable()


def _corrupt_file(path, mode):
    with open(path, "rb") as f:
        data = f.read()
    if mode == "truncate":
        data = data[: max(1, len(data) // 3)]
    else:  # bitflip: past the header, so the magic/length still parse
        i = integrity.HEADER_LEN + (len(data) - integrity.HEADER_LEN) // 2
        data = data[:i] + bytes([data[i] ^ 0x40]) + data[i + 1:]
    with open(path, "wb") as f:
        f.write(data)


class TestFraming:
    def test_roundtrip(self):
        payload = b"hello quokka" * 100
        assert integrity.unframe(integrity.frame(payload)) == payload

    @pytest.mark.parametrize("mode", ["truncate", "bitflip"])
    def test_mangled_frame_detected(self, mode, tmp_path):
        p = str(tmp_path / "a.bin")
        integrity.write_framed_atomic(p, b"x" * 4096)
        _corrupt_file(p, mode)
        with pytest.raises(CorruptArtifactError):
            integrity.read_framed(p)

    def test_bad_magic_detected(self):
        with pytest.raises(CorruptArtifactError):
            integrity.unframe(b"NOTAFRAME" + b"x" * 64)


class TestHBQCorruption:
    @pytest.mark.parametrize("mode", ["truncate", "bitflip"])
    def test_corrupt_spill_quarantined_and_lost(self, tmp_path, mode):
        from quokka_tpu.runtime.hbq import HBQ

        hbq = HBQ(str(tmp_path))
        name = (0, 1, 2, 3, 0, 4)
        hbq.put(name, pa.table({"a": [1, 2, 3]}))
        path = os.path.join(hbq.path, hbq._fname(name))
        _corrupt_file(path, mode)
        before = obs.REGISTRY.counter("integrity.corrupt").value
        assert hbq.get(name) is None  # loss, not ArrowInvalid / bad data
        assert obs.REGISTRY.counter("integrity.corrupt").value == before + 1
        # quarantined: the next existence probe reports it gone, so
        # recovery regenerates instead of retrying the bad file forever
        assert not hbq.contains(name)
        assert os.path.exists(path + ".corrupt")

    def test_namespaced_wipe_sweeps_quarantine_and_tmp(self, tmp_path):
        """Query teardown in a shared spill dir must also remove this
        namespace's quarantined .corrupt and stale .tmp leftovers — a
        long-lived service would otherwise leak them forever."""
        from quokka_tpu.runtime.hbq import HBQ

        hbq = HBQ(str(tmp_path), namespace="q1")
        other = HBQ(str(tmp_path), namespace="q2")
        name = (0, 0, 0, 1, 0, 0)
        hbq.put(name, pa.table({"a": [1]}))
        other.put(name, pa.table({"a": [2]}))
        p = os.path.join(hbq.path, hbq._fname(name))
        _corrupt_file(p, "bitflip")
        assert hbq.get(name) is None  # quarantined to .corrupt
        with open(p + ".tmp", "wb") as f:
            f.write(b"stale")  # crashed-writer leftover
        hbq.wipe()
        left = os.listdir(str(tmp_path))
        assert all(not f.startswith("hbq-q1-") for f in left), left
        assert other.contains(name)  # the neighbor's spill is untouched

    def test_healthy_roundtrip_still_works(self, tmp_path):
        from quokka_tpu.runtime.hbq import HBQ

        hbq = HBQ(str(tmp_path))
        t = pa.table({"a": [1, 2, 3], "b": ["x", "y", "z"]})
        hbq.put((0, 0, 0, 1, 0, 0), t)
        assert hbq.get((0, 0, 0, 1, 0, 0)).equals(t)


class TestCheckpointCorruption:
    @pytest.mark.parametrize("mode", ["truncate", "bitflip"])
    def test_corrupt_local_checkpoint_raises_named_error(self, tmp_path, mode):
        from quokka_tpu.runtime.ckptstore import CheckpointStore

        cs = CheckpointStore(str(tmp_path))
        cs.save(1, 0, 4, b"state-bytes" * 200)
        assert cs.load(1, 0, 4) == b"state-bytes" * 200
        _corrupt_file(cs._path(1, 0, 4), mode)
        before = obs.REGISTRY.counter("integrity.corrupt").value
        with pytest.raises(CorruptArtifactError):
            cs.load(1, 0, 4)
        assert obs.REGISTRY.counter("integrity.corrupt").value == before + 1
        # quarantined -> subsequent loads see it as ABSENT (treated as loss)
        assert cs.load(1, 0, 4) is None

    def test_remote_save_never_exposes_partial_object(self):
        """The fsspec path writes a tmp key then moves it into place: at no
        point does a partial object exist under the final key, and the
        landed bytes are re-read and checksum-verified."""
        from quokka_tpu.runtime.ckptstore import CheckpointStore

        root = "memory://qk-ckpt-atomic"
        cs = CheckpointStore(root, namespace="q1")
        data = b"snapshot" * 500
        cs.save(2, 1, 6, data)
        assert cs.load(2, 1, 6) == data
        fs, base = cs._fs()
        names = fs.glob(f"{base}/ckpt-q1-*")
        assert len(names) == 1 and names[0].endswith(".pkl")  # no tmp litter
        cs.wipe_namespace()
        assert cs.load(2, 1, 6) is None

    def test_remote_partial_object_is_loss_not_data(self):
        """A torn write under the final key (what the old direct-write path
        could leave) fails the frame check: quarantined + named error."""
        from quokka_tpu.runtime.ckptstore import CheckpointStore

        root = "memory://qk-ckpt-torn"
        cs = CheckpointStore(root, namespace="q2")
        cs.save(0, 0, 2, b"real-state" * 100)
        fs, base = cs._fs()
        path = f"{base}/ckpt-q2-0-0-2.pkl"
        with fs.open(path, "wb") as f:
            f.write(fs.cat_file(path)[:37])  # torn mid-upload
        with pytest.raises(CorruptArtifactError):
            cs.load(0, 0, 2)
        assert cs.load(0, 0, 2) is None  # quarantined away
        cs.wipe_namespace()


class _Target:
    def __init__(self):
        self._lock = threading.RLock()
        self.calls = []

    def bump(self, x):
        self.calls.append(x)
        return x * 2


class TestRpcResilience:
    def test_transport_error_is_named_and_distinct_from_auth(self):
        from quokka_tpu.runtime.rpc import RpcAuthError

        assert issubclass(RpcTransportError, ConnectionError)
        assert not issubclass(RpcTransportError, RpcAuthError)
        assert not issubclass(RpcAuthError, RpcTransportError)

    def test_reconnect_after_disconnect(self):
        from quokka_tpu.runtime.rpc import RpcClient, RpcServer

        t = _Target()
        srv = RpcServer(t, token="s")
        try:
            cli = RpcClient(srv.address, token="s")
            assert cli.call("bump", 1) == 2
            cli._sock.close()  # connection dies under the client
            assert cli.call("bump", 2) == 4  # transparent reconnect
            assert t.calls == [1, 2]
            cli.close()
        finally:
            srv.close()

    def test_retried_request_id_dedups_server_side(self):
        """Replay the exact wire protocol: the same (client_id, req_id)
        resent — including over a brand-new connection, the
        lost-response-then-reconnect case — executes the mutation ONCE and
        returns the cached response."""
        from quokka_tpu.runtime import rpc as rpcmod

        t = _Target()
        srv = rpcmod.RpcServer(t, token="s")

        def dial():
            s = socket.create_connection(srv.address, timeout=10)
            rpcmod._client_handshake(s, "s")
            return s

        try:
            s1 = dial()
            rpcmod._send_msg(s1, ("cid-1", 1, "bump", (21,)))
            assert rpcmod._recv_msg(s1) == (True, 42)
            # retry on the SAME connection (response was lost in flight)
            rpcmod._send_msg(s1, ("cid-1", 1, "bump", (21,)))
            assert rpcmod._recv_msg(s1) == (True, 42)
            s1.close()
            # retry across a reconnect (connection died before the reply)
            s2 = dial()
            rpcmod._send_msg(s2, ("cid-1", 1, "bump", (21,)))
            assert rpcmod._recv_msg(s2) == (True, 42)
            s2.close()
            assert t.calls == [21]  # applied exactly once
        finally:
            srv.close()

    def test_chaos_drops_with_dedup_apply_once(self):
        """Seeded chaos connection drops (pre- and post-send): every call
        still returns the right answer and every mutation applies once."""
        from quokka_tpu.runtime.rpc import RpcClient, RpcServer

        t = _Target()
        srv = RpcServer(t, token="s")
        try:
            cli = RpcClient(srv.address, token="s")
            CHAOS.configure("seed=7,rpc=0.2")
            vals = [cli.call("bump", i) for i in range(40)]
            CHAOS.disable()
            assert vals == [i * 2 for i in range(40)]
            assert t.calls == list(range(40))
            cli.close()
        finally:
            srv.close()

    def test_concurrent_replay_waits_for_inflight_execution(self):
        """A retried request that lands while the ORIGINAL is still
        executing must wait for it, not re-execute the mutation
        concurrently (the fast-reconnect double-apply race)."""
        import time

        from quokka_tpu.runtime import rpc as rpcmod

        class Slow:
            def __init__(self):
                self._lock = threading.RLock()
                self.calls = 0

            def slow_bump(self, x):
                self.calls += 1
                time.sleep(0.6)
                return x + 1

        t = Slow()
        srv = rpcmod.RpcServer(t, token="s")

        def dial():
            s = socket.create_connection(srv.address, timeout=10)
            rpcmod._client_handshake(s, "s")
            return s

        try:
            s1, s2 = dial(), dial()
            rpcmod._send_msg(s1, ("cid-r", 5, "slow_bump", (1,)))
            time.sleep(0.1)  # original is mid-execution
            rpcmod._send_msg(s2, ("cid-r", 5, "slow_bump", (1,)))
            results = {}

            def read(sock, key):
                results[key] = rpcmod._recv_msg(sock)

            th = [threading.Thread(target=read, args=(s1, "a")),
                  threading.Thread(target=read, args=(s2, "b"))]
            for x in th:
                x.start()
            for x in th:
                x.join(timeout=10)
            assert results == {"a": (True, 2), "b": (True, 2)}
            assert t.calls == 1  # the replay waited; applied exactly once
            s1.close(), s2.close()
        finally:
            srv.close()

    @pytest.mark.parametrize("declared,expect_calls", [(True, 2), (False, 1)],
                             ids=["reexecutable", "default"])
    def test_large_response_tombstone_is_opt_in(self, declared, expect_calls):
        """Responses over the dedup size cap are tombstoned (re-executed on
        replay, not pinned in server memory) ONLY for methods the server
        declared re-executable idempotent reads.  By default even a huge
        response is cached whole: a destructive call (ntt_pop) replayed
        against a tombstone would pop — and silently lose — a second task."""
        from quokka_tpu.runtime import rpc as rpcmod

        class Bulk:
            def __init__(self):
                self._lock = threading.RLock()
                self.calls = 0

            def big_read(self):
                self.calls += 1
                return b"z" * (2 << 20)

        t = Bulk()
        srv = rpcmod.RpcServer(
            t, token="s",
            reexecutable=frozenset({"big_read"}) if declared else None)
        try:
            s = socket.create_connection(srv.address, timeout=10)
            rpcmod._client_handshake(s, "s")
            rpcmod._send_msg(s, ("cid-b", 1, "big_read", ()))
            assert rpcmod._recv_msg(s)[1] == b"z" * (2 << 20)
            rpcmod._send_msg(s, ("cid-b", 1, "big_read", ()))
            assert rpcmod._recv_msg(s)[1] == b"z" * (2 << 20)
            assert t.calls == expect_calls
            s.close()
        finally:
            srv.close()

    def test_dead_peer_fails_fast_with_transport_error(self):
        from quokka_tpu.runtime.rpc import RpcClient, RpcServer

        t = _Target()
        srv = RpcServer(t, token="s")
        cli = RpcClient(srv.address, token="s")
        srv.close()
        cli._drop_sock()  # force the next call through a reconnect
        with pytest.raises(RpcTransportError):
            cli.call("bump", 1)


class TestStoreRetry:
    def test_flaky_store_calls_retried_to_success(self):
        from quokka_tpu.runtime.store_service import (
            ControlStoreClient,
            CoordinatorStore,
            serve_store,
        )

        cs = CoordinatorStore()
        srv = serve_store(cs)
        try:
            cli = ControlStoreClient(srv.address)
            CHAOS.configure("seed=3,store=0.4")
            before = obs.REGISTRY.counter("store.retry").value
            for i in range(30):
                cli.set(f"k{i}", i)
            with cli.transaction():
                cli.tset("LIT", (0, 0), 7)
                cli.tset("LIT", (0, 1), 9)
            CHAOS.disable()
            assert [cli.get(f"k{i}") for i in range(30)] == list(range(30))
            assert cli.tget("LIT", (0, 0)) == 7
            assert obs.REGISTRY.counter("store.retry").value > before
            cli.close()
        finally:
            srv.close()

    def test_exhausted_transient_errors_surface(self):
        from quokka_tpu.runtime.store_service import (
            ControlStoreClient,
            CoordinatorStore,
            serve_store,
        )

        cs = CoordinatorStore()
        srv = serve_store(cs)
        try:
            cli = ControlStoreClient(srv.address)
            CHAOS.configure("seed=3,store=1.0")  # every attempt fails
            with pytest.raises(TransientStoreError):
                cli.set("k", 1)
            CHAOS.disable()
            cli.close()
        finally:
            srv.close()


class TestChaosSpec:
    def test_parse_render_roundtrip(self):
        cfg = ChaosConfig.parse("seed=42,rpc=0.02,corrupt=0.01,kill=1")
        assert cfg.seed == 42 and cfg.kill == 1
        assert cfg.prob("rpc") == 0.02
        assert cfg.prob("spill") == 0.01  # corrupt covers both sites
        cfg2 = ChaosConfig.parse(cfg.render())
        assert cfg2.render() == cfg.render()

    def test_site_overrides(self):
        cfg = ChaosConfig.parse("seed=1,corrupt=0.1,corrupt_ckpt=0.9")
        assert cfg.prob("spill") == 0.1 and cfg.prob("ckpt") == 0.9
        # an EXPLICIT zero override beats the blanket rate (falsy-zero must
        # not fall through an `or`)
        cfg = ChaosConfig.parse("seed=1,corrupt=0.3,corrupt_spill=0")
        assert cfg.prob("spill") == 0.0 and cfg.prob("ckpt") == 0.3

    def test_unknown_key_rejected(self):
        with pytest.raises(ChaosSpecError):
            ChaosConfig.parse("seed=1,typo_rate=0.5")

    def test_same_seed_same_plan(self):
        a, b = ChaosConfig.parse("seed=9,kill=2"), None
        CHAOS.configure(a)
        p1 = CHAOS.plan_embedded_failures([(1, 0), (1, 1), (2, 0)])
        CHAOS.configure(ChaosConfig.parse("seed=9,kill=2"))
        p2 = CHAOS.plan_embedded_failures([(1, 0), (1, 1), (2, 0)])
        assert p1 == p2 and p1


# -- end-to-end corruption matrix -------------------------------------------


def _make_table(n=8000):
    r = np.random.default_rng(5)
    # integer-valued floats: sums are exact under any execution order, so
    # the bit-exact assertion is a real claim, not a tolerance
    return pa.table({"k": r.integers(0, 40, n).astype(np.int64),
                     "v": r.integers(0, 100, n).astype(np.float64)})


def _agg(ctx, table, **cfg):
    for k, v in cfg.items():
        ctx.set_config(k, v)
    s = ctx.read_dataset(InputArrowDataset(table, batch_rows=512))
    return (s.groupby("k").agg_sql("sum(v) as sv, count(*) as n")
            .collect().sort_values("k").reset_index(drop=True))


class TestCorruptionE2E:
    """Every artifact write corrupted (prob 1.0) + a mid-run channel loss:
    results must stay bit-exact AND the corruption-detected counter must
    move (silent acceptance of bad bytes would pass a looser test)."""

    @pytest.mark.parametrize("site,cfg", [
        ("spill", dict(checkpoint_interval=None,
                       inject_failure={"after_tasks": 12,
                                       "channels": [(1, 0), (1, 1)]})),
        ("ckpt", dict(checkpoint_interval=3,
                      inject_failure={"after_tasks": 10,
                                      "channels": [(1, 0)]})),
    ], ids=["spill", "ckpt"])
    def test_corrupt_artifacts_detected_and_bit_exact(self, tmp_path, site,
                                                      cfg):
        table = _make_table()
        baseline = _agg(QuokkaContext(), table)
        before = obs.REGISTRY.counter("integrity.corrupt").value
        CHAOS.configure(f"seed=99,corrupt_{site}=1.0")
        try:
            got = _agg(QuokkaContext(), table, fault_tolerance=True,
                       hbq_path=str(tmp_path), **cfg)
        finally:
            CHAOS.disable()
        pd.testing.assert_frame_equal(got, baseline, check_exact=True,
                                      check_dtype=False)
        assert obs.REGISTRY.counter("integrity.corrupt").value > before

    def test_chaos_kill_without_scripts(self, tmp_path):
        """kill=N alone (no scripted inject_failure): seeded random exec
        channels are lost at seeded task boundaries and recovered."""
        table = _make_table()
        baseline = _agg(QuokkaContext(), table)
        before = obs.REGISTRY.counter("chaos.kill").value
        CHAOS.configure("seed=31,kill=2,kill_after=8,corrupt=0.2")
        try:
            got = _agg(QuokkaContext(), table, fault_tolerance=True,
                       hbq_path=str(tmp_path), checkpoint_interval=3)
        finally:
            CHAOS.disable()
        pd.testing.assert_frame_equal(got, baseline, check_exact=True,
                                      check_dtype=False)
        assert obs.REGISTRY.counter("chaos.kill").value > before
