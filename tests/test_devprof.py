"""Device-time & roofline plane (obs/devprof.py + planner/cost.py seconds
basis): static cost extraction, calibration profile lifecycle, roofline
math, snapshot attachment, Prometheus families, and the per-query skew
gauge reset (the never-shrinking global max regression)."""

import json
import os

import pytest

from quokka_tpu import obs
from quokka_tpu import logical
from quokka_tpu.obs import devprof
from quokka_tpu.obs import explain
from quokka_tpu.obs import export
from quokka_tpu.obs import opstats
from quokka_tpu.obs.metrics import Registry
from quokka_tpu.obs.opstats import OpStats
from quokka_tpu.planner import cost as pcost


class _Compiled:
    """Stands in for a compiled executable: cost_analysis() returns the
    list-of-dicts shape jax produces."""

    def __init__(self, flops=1000.0, nbytes=500.0, out=100.0):
        self._ca = {"flops": flops, "bytes accessed": nbytes,
                    "bytes accessedout{}": out}

    def cost_analysis(self):
        return [self._ca]


class _Broken:
    def cost_analysis(self):
        raise RuntimeError("no analysis on this backend")


@pytest.fixture(autouse=True)
def _clean_devprof():
    devprof.reset()
    yield
    devprof.reset()
    if hasattr(opstats._CUR, "key"):
        del opstats._CUR.key


# -- static cost extraction ---------------------------------------------------


class TestCostExtraction:
    def test_known_answer_from_real_executable(self):
        """XLA's static figures for a 128x128 f32 matmul+add: 2*n^3 + n^2
        flops; exactly what the bench smoke relies on for every fused
        program."""
        import jax
        import jax.numpy as jnp

        n = 128
        a = jnp.ones((n, n), dtype=jnp.float32)
        fn = jax.jit(lambda x, y: x @ y + y)
        compiled = fn.lower(a, a).compile()
        cost = devprof.extract_cost(compiled)
        assert cost is not None
        assert cost["flops"] == 2 * n**3 + n**2  # 4210688
        assert cost["bytes"] > 0
        assert cost["out_bytes"] >= n * n * 4  # at least the f32 result

    def test_extract_handles_failure_and_junk(self):
        assert devprof.extract_cost(_Broken()) is None
        c = devprof.extract_cost(
            _Compiled(flops=float("nan"), nbytes=-5, out=0))
        assert c == {"flops": 0.0, "bytes": 0.0, "out_bytes": 0.0}

    def test_record_and_sidecar_roundtrip(self, tmp_path):
        art = str(tmp_path / "prog.bin")
        before = obs.REGISTRY.counter("devprof.programs_costed").value
        devprof.record_cost("k1", _Compiled(), path=art)
        assert devprof.program_cost("k1") == {
            "flops": 1000.0, "bytes": 500.0, "out_bytes": 100.0}
        assert obs.REGISTRY.counter(
            "devprof.programs_costed").value == before + 1
        sidecar = art + ".cost.json"
        assert os.path.exists(sidecar)
        # cache-hit replay: fresh process state loads the sidecar verbatim
        devprof.reset()
        assert devprof.program_cost("k1") is None
        assert devprof.load_cost("k1", art) is True
        assert devprof.program_cost("k1")["flops"] == 1000.0

    def test_corrupt_sidecar_leaves_program_uncosted(self, tmp_path):
        art = str(tmp_path / "prog.bin")
        with open(art + ".cost.json", "w") as f:
            f.write("{not json")
        assert devprof.load_cost("k1", art) is False
        with open(art + ".cost.json", "w") as f:
            json.dump({"version": 999, "flops": 1, "bytes": 1,
                       "out_bytes": 0}, f)
        assert devprof.load_cost("k1", art) is False
        assert devprof.program_cost("k1") is None

    def test_costs_snapshot_sorts_and_tallies(self):
        devprof.record_cost(("a",), _Compiled(flops=10.0))
        devprof.record_cost(("b",), _Compiled(flops=99.0, nbytes=11.0))
        devprof.on_dispatch(("b",))
        devprof.on_dispatch(("b",))
        snap = devprof.costs_snapshot()
        assert [r["flops"] for r in snap] == [99.0, 10.0]
        assert snap[0]["dispatches"] == 2
        assert snap[0]["intensity"] == 99.0 / 11.0


# -- calibration profile lifecycle --------------------------------------------


class TestCalibration:
    def test_calibrate_persists_and_reloads(self, tmp_path, monkeypatch):
        monkeypatch.setenv("QK_DEVPROF_DIR", str(tmp_path))
        prof = devprof.calibrate()
        assert prof["peak_flops_s"] > 0 and prof["peak_bw_bytes_s"] > 0
        path = os.path.join(str(tmp_path), f"{prof['fingerprint']}.json")
        assert os.path.exists(path)
        # peaks mirrored onto gauges for /metrics
        assert obs.REGISTRY.gauge("devprof.peak_flops").value == \
            prof["peak_flops_s"]
        # a fresh process (reset) lazily reloads the same profile
        devprof.reset()
        p2 = devprof.peaks()
        assert p2 is not None and p2["peak_flops_s"] == prof["peak_flops_s"]
        assert devprof.planning_bw() == prof["peak_bw_bytes_s"]

    def test_foreign_fingerprint_rejected_wholesale(self, tmp_path,
                                                    monkeypatch):
        monkeypatch.setenv("QK_DEVPROF_DIR", str(tmp_path))
        prof = devprof.calibrate()
        path = os.path.join(str(tmp_path), f"{prof['fingerprint']}.json")
        data = json.load(open(path))
        data["fingerprint"] = "tpu-8x-deadbeef"
        os.rename(path, os.path.join(
            str(tmp_path), f"{devprof._fingerprint()}.json"))
        json.dump(data, open(path, "w"))
        devprof.reset()
        assert devprof.peaks() is None
        assert devprof.planning_bw() is None

    def test_corrupt_or_versioned_away_profile_rejected(self, tmp_path,
                                                        monkeypatch):
        monkeypatch.setenv("QK_DEVPROF_DIR", str(tmp_path))
        path = os.path.join(str(tmp_path), f"{devprof._fingerprint()}.json")
        with open(path, "w") as f:
            f.write("{torn write")
        assert devprof.peaks() is None
        devprof.reset()
        json.dump({"version": -1, "fingerprint": devprof._fingerprint(),
                   "peak_flops_s": 1.0, "peak_bw_bytes_s": 1.0,
                   "sources": {}}, open(path, "w"))
        assert devprof.peaks() is None

    def test_ensure_calibrated_honors_skip_knob(self, tmp_path, monkeypatch):
        monkeypatch.setenv("QK_DEVPROF_DIR", str(tmp_path))
        monkeypatch.setenv("QK_DEVPROF_CALIBRATE", "0")
        assert devprof.ensure_calibrated() == {}
        assert devprof.peaks() is None

    def test_persistence_disabled_by_empty_dir(self, monkeypatch):
        monkeypatch.setenv("QK_DEVPROF_DIR", "")
        assert devprof._dir() is None
        prof = devprof.calibrate()  # in-process only, no file writes
        assert prof["peak_flops_s"] > 0
        devprof.reset()
        assert devprof.peaks() is None  # nothing persisted to reload


# -- roofline math ------------------------------------------------------------


class TestRoofline:
    def test_compute_bound(self):
        r = devprof.roofline(1e9, 1e6, 1.0, 1e10, 1e9)
        assert r["intensity"] == 1000.0
        assert r["achieved_flops_s"] == 1e9
        # intensity*bw = 1e12 > peak 1e10 -> judged against the FLOP ceiling
        assert r["efficiency"] == pytest.approx(0.1)

    def test_memory_bound(self):
        r = devprof.roofline(1e6, 1e9, 1.0, 1e12, 1e10)
        # attainable = intensity(1e-3) * bw(1e10) = 1e7 FLOP/s
        assert r["efficiency"] == pytest.approx(1e6 / 1e7)
        assert r["achieved_bw_s"] == 1e9

    def test_pure_data_movement_judged_on_bandwidth(self):
        r = devprof.roofline(0.0, 5e8, 1.0, 1e12, 1e9)
        assert r["intensity"] == 0.0
        assert r["achieved_flops_s"] is None
        assert r["efficiency"] == pytest.approx(0.5)

    def test_degenerate_inputs(self):
        assert devprof.roofline(0, 0, 1.0, 1e9, 1e9)["efficiency"] is None
        assert devprof.roofline(1e6, 1e6, None, 1e9, 1e9)["efficiency"] is \
            None
        assert devprof.roofline(1e6, 1e6, 0.0, 1e9, 1e9)["efficiency"] is \
            None
        # uncalibrated: achieved rates still reported, efficiency unknowable
        r = devprof.roofline(1e6, 1e6, 1.0, None, None)
        assert r["achieved_flops_s"] == 1e6 and r["efficiency"] is None


# -- seconds basis in the cost model ------------------------------------------


class _Reader:
    def __init__(self, hint=80000):
        self._hint = hint

    def size_hint(self):
        return self._hint


def _source_plan(rows_measured=None):
    src = logical.SourceNode(_Reader(), ["k", "v"])
    sub = {0: src}
    sig = pcost.source_signature(src.reader, None, None)
    profile = {}
    if rows_measured is not None:
        profile[sig] = {"rows": rows_measured, "bytes": rows_measured * 16.0}
    return sub, sig, profile


def _install_peaks(bw=1e10, sources=None):
    devprof._install({
        "version": 1, "fingerprint": "test-fp",
        "peak_flops_s": 1e12, "peak_bw_bytes_s": bw,
        "sources": sources or {},
    })


class TestSecondsBasis:
    def test_hint_when_uncalibrated(self):
        sub, _, profile = _source_plan(rows_measured=1000)
        model = pcost.CostModel(sub, profile=profile)
        sec = model.estimate_seconds(0)
        assert sec.basis == pcost.SECONDS_HINT
        assert sec.seconds == pytest.approx(16000.0 / pcost._NOMINAL_BW)
        assert not pcost.seconds_usable(sec.basis)

    def test_roofline_conversion_over_measured_bytes(self):
        sub, _, profile = _source_plan(rows_measured=1000)
        _install_peaks(bw=1e10)
        model = pcost.CostModel(sub, profile=profile)
        sec = model.estimate_seconds(0)
        assert sec.basis == pcost.SECONDS_ROOFLINE
        assert sec.seconds == pytest.approx(16000.0 / 1e10)
        assert pcost.seconds_usable(sec.basis)

    def test_measured_scan_seconds_win(self):
        sub, sig, profile = _source_plan(rows_measured=1000)
        _install_peaks(sources={sig: {"seconds": 0.125, "bytes": 16000.0}})
        model = pcost.CostModel(sub, profile=profile)
        sec = model.estimate_seconds(0)
        assert sec.basis == pcost.SECONDS_MEASURED
        assert sec.seconds == 0.125

    def test_conversion_capped_by_cardinality_basis(self):
        """Converting *guessed* bytes through a calibrated peak is still a
        guess: the seconds basis can never outrank the rows/bytes basis."""
        sub, _, profile = _source_plan(rows_measured=None)  # hint-only
        _install_peaks(bw=1e10)
        model = pcost.CostModel(sub, profile={})
        sec = model.estimate_seconds(0)
        assert sec.est.basis == pcost.BASIS_HINT
        assert sec.basis == pcost.SECONDS_HINT
        assert not pcost.seconds_usable(sec.basis)

    def test_observed_bandwidth_preferred_over_calibrated_peak(self):
        devprof._install({
            "version": 1, "fingerprint": "test-fp",
            "peak_flops_s": 1e12, "peak_bw_bytes_s": 1e10,
            "observed_bw_bytes_s": 2e9, "sources": {},
        })
        assert devprof.planning_bw() == 2e9


# -- snapshot attachment + explain render -------------------------------------


class _Actor:
    def __init__(self, kind, channels=2, targets=(), stage=0):
        self.kind = kind
        self.channels = channels
        self.targets = {t: None for t in targets}
        self.stage = stage
        self.reader = _Reader()  # input actors carry their reader


class _Graph:
    def __init__(self, qid, actors, plan_fp="fp-test"):
        self.query_id = qid
        self.actors = actors
        self.plan_fp = plan_fp


def _run_attributed_query(s, qid="qeff"):
    """One operator (actor 1) runs 0.5s and dispatches a costed program
    twice: 2000 flops over 1000 bytes."""
    s.register_plan(_Graph(qid, {
        0: _Actor("input", targets=(1,)),
        1: _Actor("exec", stage=1),
    }))
    devprof.record_cost("prog", _Compiled(flops=1000.0, nbytes=500.0))
    opstats._CUR.key = (qid, 1, 0)
    devprof.on_dispatch("prog")
    devprof.on_dispatch("prog")
    del opstats._CUR.key
    s.dispatch_time(qid, 1, 0, 0.5)
    s.exec_out(qid, 1, 0, 10)


class TestAttach:
    def test_snapshot_gains_efficiency_section(self):
        _install_peaks(bw=1e10)
        s = OpStats()
        _run_attributed_query(s)
        snap = s.snapshot("qeff")
        eff = snap["efficiency"]
        assert eff["peaks"]["fingerprint"] == "test-fp"
        (row,) = [r for r in eff["operators"] if r["actor"] == 1]
        assert row["flops"] == 2000.0 and row["bytes"] == 1000.0
        assert row["program_dispatches"] == 2
        assert row["achieved_flops_s"] == pytest.approx(4000.0)
        # intensity 2.0 -> attainable = 2 * 1e10 = 2e10 (memory-bound)
        assert row["efficiency"] == pytest.approx(4000.0 / 2e10)
        assert row["flagged"] is True  # far below the 5% floor
        g = obs.REGISTRY.gauge("devprof.eff.qeff.a1")
        assert g.value == pytest.approx(row["efficiency"])
        # explain() renders the section with the floor flag
        text = explain.render(snap)
        assert "device efficiency" in text
        assert "** BELOW QK_EFF_FLOOR **" in text
        assert "roofline=" in text
        det = explain.efficiency_detail(snap)
        assert det["operators"][0]["efficiency"] == row["efficiency"]
        s.reset()

    def test_uncalibrated_attach_still_reports_rates(self):
        s = OpStats()
        _run_attributed_query(s, qid="qunc")
        snap = s.snapshot("qunc")
        (row,) = [r for r in snap["efficiency"]["operators"]
                  if r["actor"] == 1]
        assert row["achieved_flops_s"] == pytest.approx(4000.0)
        assert row["efficiency"] is None and row["flagged"] is False
        assert "uncalibrated" in explain.render(snap)
        s.reset()

    def test_query_gc_drops_attribution_and_gauges(self):
        _install_peaks()
        s = OpStats()
        _run_attributed_query(s, qid="qgc")
        s.snapshot("qgc")
        assert "devprof.eff.qgc.a1" in obs.REGISTRY.snapshot()
        s.on_query_gc("qgc")
        assert "devprof.eff.qgc.a1" not in obs.REGISTRY.snapshot()
        with devprof._lock:
            assert not any(k[0] == "qgc" for k in devprof._attr)
        s.reset()

    def test_summary_digest(self):
        _install_peaks()
        devprof.record_cost("p", _Compiled())
        devprof.on_dispatch("p")
        d = devprof.summary()
        assert d["calibrated"] is True
        assert d["programs_costed"] == 1 and d["program_dispatches"] == 1


# -- Prometheus families ------------------------------------------------------


class TestPromFamilies:
    def test_roofline_gauge_renders_as_labeled_family(self):
        r = Registry()
        r.gauge('devprof.eff.q"1.a0').set(0.25)
        text = export.render(r)
        assert ('quokka_devprof_roofline_efficiency'
                '{op="q\\"1.a0"} 0.25') in text

    def test_peaks_render_as_exact_families(self):
        r = Registry()
        r.gauge("devprof.peak_flops").set(1e12)
        r.gauge("devprof.peak_bw_bytes").set(5e10)
        text = export.render(r)
        assert "quokka_devprof_peak_flops 1000000000000" in text
        assert "quokka_devprof_peak_bw_bytes 50000000000" in text
        # the process-wide peaks must never fold into the labeled family
        assert 'quokka_devprof_peak_flops{' not in text

    def test_programs_costed_counter_renders(self):
        r = Registry()
        r.counter("devprof.programs_costed").inc(3)
        text = export.render(r)
        assert "quokka_devprof_programs_costed_total 3" in text


# -- satellite: per-query skew gauge reset ------------------------------------


class TestSkewGaugeReset:
    def test_global_skew_gauge_tracks_live_queries_only(self):
        """Regression: the global shuffle.skew gauge was a process-lifetime
        ratchet (set(max(old, new))) — one skewed query pinned it forever
        and /health skew alerts never cleared.  It must drop to the worst
        LIVE query at GC, and to 0 when idle."""
        s = OpStats()
        for qid in ("qa", "qb"):
            s.register_plan(_Graph(qid, {
                0: _Actor("input", targets=(1,)),
                1: _Actor("exec", stage=1),
            }))
        # qa: 900/100 over 2 channels -> ratio 1.8; qb: 600/400 -> 1.2
        s.edge("qa", 0, 1, 0, 900)
        s.edge("qa", 0, 1, 1, 100)
        s.edge("qb", 0, 1, 0, 600)
        s.edge("qb", 0, 1, 1, 400)
        s.snapshot("qa")
        s.snapshot("qb")
        g = obs.REGISTRY.gauge("shuffle.skew")
        assert g.value == pytest.approx(1.8)
        s.on_query_gc("qa")
        assert g.value == pytest.approx(1.2)  # worst LIVE query, not ratchet
        s.on_query_gc("qb")
        assert g.value == 0.0
        s.reset()
