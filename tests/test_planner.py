"""Cost-based planner tests (planner/cost.py, planner/decide.py).

Three layers:
- cost-model precedence: measured cardprofile figures beat catalog samples
  beat size_hint() guesses, and derived estimates carry the weakest input
  basis so decisions stay auditable;
- the plan flip: the same query plans broadcast on a cold profile and
  partition once the (injected) cardprofile says the build side is big —
  recorded in the decision log with the measured figures and rendered by
  explain's planner-decision section;
- QK026 known-answer fixtures: adapt_salt on anything but an inner,
  non-broadcast, unordered hash join is flagged, as is a user column
  colliding with the reserved salt name.
"""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from quokka_tpu import logical, optimizer
from quokka_tpu.analysis import planck
from quokka_tpu.context import QuokkaContext
from quokka_tpu.expression import col, date
from quokka_tpu.obs import explain
from quokka_tpu.planner import cost, decide

import tpch_data


@pytest.fixture(scope="module")
def pq_env(tmp_path_factory):
    root = tmp_path_factory.mktemp("planner")
    r = np.random.default_rng(7)
    n = 20_000
    fact = pa.table({
        "fk": r.integers(0, 100, n).astype(np.int64),
        "x": r.integers(0, 1000, n).astype(np.int64),
    })
    dim = pa.table({
        "pk": np.arange(100, dtype=np.int64),
        "w": np.arange(100, dtype=np.int64) * 10,
    })
    fp, dp = str(root / "fact.parquet"), str(root / "dim.parquet")
    pq.write_table(fact, fp, row_group_size=2048)
    pq.write_table(dim, dp)
    return fp, dp


def _subplan(stream):
    sub, _ = stream.ctx._copy_subgraph(stream.node_id)
    sink = logical.SinkNode([stream.node_id], sub[stream.node_id].schema)
    sid = max(sub) + 1
    sub[sid] = sink
    return sub, sid


def _source_ids(sub):
    return [nid for nid, n in sub.items()
            if isinstance(n, logical.SourceNode)]


def _joins(sub, sid):
    return [sub[n] for n in optimizer._reachable(sub, sid)
            if isinstance(sub[n], logical.JoinNode)]


class _AnySig:
    """Profile stub answering every source signature with one record —
    sidesteps recomputing post-pushdown signatures in tests."""

    def __init__(self, rec):
        self.rec = rec

    def get(self, _sig, default=None):
        return dict(self.rec)


# -- cost-model precedence ----------------------------------------------------


class TestPrecedence:
    def test_measured_beats_everything(self, pq_env):
        fp, _ = pq_env
        ctx = QuokkaContext()
        sub, sid = _subplan(ctx.read_parquet(fp))
        (src,) = _source_ids(sub)
        model = cost.CostModel(
            sub, catalog=optimizer._get_catalog(),
            profile=_AnySig({"rows": 777, "bytes": 6216}))
        est = model.estimate(src)
        assert est.basis == cost.BASIS_MEASURED
        assert est.rows == 777 and est.bytes == 6216

    def test_sampled_beats_hint(self, pq_env):
        fp, _ = pq_env
        ctx = QuokkaContext()
        sub, sid = _subplan(ctx.read_parquet(fp))
        (src,) = _source_ids(sub)
        est = cost.CostModel(sub, catalog=optimizer._get_catalog(),
                             profile={}).estimate(src)
        assert est.basis == cost.BASIS_SAMPLED
        assert est.rows == pytest.approx(20_000, rel=0.05)

    def test_hint_is_the_floor(self, pq_env):
        fp, _ = pq_env
        ctx = QuokkaContext()
        sub, sid = _subplan(ctx.read_parquet(fp))
        (src,) = _source_ids(sub)
        est = cost.CostModel(sub, catalog=None, profile={}).estimate(src)
        assert est.basis == cost.BASIS_HINT
        assert est.rows > 0  # synthesized from size_hint() bytes

    def test_filter_keeps_basis_and_reduces(self, pq_env):
        fp, _ = pq_env
        ctx = QuokkaContext(optimize=False)
        q = ctx.read_parquet(fp).filter(col("x") > 10)
        sub, sid = _subplan(q)
        (src,) = _source_ids(sub)
        model = cost.CostModel(sub, catalog=None,
                               profile=_AnySig({"rows": 1000, "bytes": 8000}))
        (flt,) = [nid for nid, n in sub.items()
                  if isinstance(n, logical.FilterNode)]
        est = model.estimate(flt)
        assert est.basis == cost.BASIS_MEASURED
        assert est.rows == pytest.approx(1000 * cost.FILTER_SELECTIVITY)

    def test_join_carries_weakest_input_basis(self, pq_env):
        fp, dp = pq_env
        ctx = QuokkaContext(optimize=False)
        q = ctx.read_parquet(fp).join(ctx.read_parquet(dp),
                                      left_on="fk", right_on="pk")
        sub, sid = _subplan(q)
        (join,) = [nid for nid, n in sub.items()
                   if isinstance(n, logical.JoinNode)]
        # no catalog, no profile: both inputs are hint-basis guesses
        est = cost.CostModel(sub, catalog=None, profile={}).estimate(join)
        assert est.basis == cost.BASIS_HINT
        assert cost._weaker(cost.BASIS_MEASURED, cost.BASIS_HINT) \
            == cost.BASIS_HINT
        assert cost._weaker(cost.BASIS_MEASURED, cost.BASIS_SAMPLED) \
            == cost.BASIS_SAMPLED

    def test_source_signature_is_plan_independent(self, pq_env):
        fp, _ = pq_env
        ctx = QuokkaContext()
        sub, _ = _subplan(ctx.read_parquet(fp))
        (src,) = _source_ids(sub)
        node = sub[src]
        a = cost.source_signature(node.reader, node.predicate,
                                  node.projection)
        b = cost.source_signature(node.reader, node.predicate,
                                  node.projection)
        assert a == b
        assert cost.source_signature(node.reader, col("x") > 5,
                                     node.projection) != a


# -- the plan flip ------------------------------------------------------------


class TestPlanFlip:
    def _optimize(self, pq_env, monkeypatch, profile):
        from quokka_tpu.obs import opstats

        monkeypatch.setattr(opstats, "measured_sources", lambda: profile)
        fp, dp = pq_env
        ctx = QuokkaContext()
        q = ctx.read_parquet(fp).join(ctx.read_parquet(dp),
                                      left_on="fk", right_on="pk")
        sub, sid = _subplan(q)
        decide.begin_decisions()
        optimizer.optimize(sub, sid)
        return _joins(sub, sid), decide.take_decisions()

    def test_cold_broadcasts_warm_partitions(self, pq_env, monkeypatch):
        monkeypatch.setenv("QK_BROADCAST_BYTES", str(1 << 20))
        # cold: the 100-row dim samples far under the legacy row threshold
        joins, cold_log = self._optimize(pq_env, monkeypatch, {})
        assert joins and joins[0].broadcast
        cold = [d for d in cold_log if d["kind"] == "broadcast"]
        assert cold and cold[0]["choice"] == "broadcast"
        assert cold[0]["basis"] != cost.BASIS_MEASURED
        # warm: a measured profile says the build side is 4 MiB — over the
        # byte threshold, the SAME query must flip to partition
        joins, warm_log = self._optimize(
            pq_env, monkeypatch,
            _AnySig({"rows": 500_000, "bytes": 4 << 20}))
        assert joins and not joins[0].broadcast
        warm = [d for d in warm_log if d["kind"] == "broadcast"]
        assert warm and warm[0]["choice"] == "partition"
        assert warm[0]["basis"] == cost.BASIS_MEASURED
        assert warm[0]["build_bytes"] > warm[0]["threshold_bytes"]
        # the flip is render-able: explain's decision line carries the
        # measured figures that drove it
        line = explain._decision_line(warm[0])
        assert "partition" in line and "basis=measured" in line

    def test_measured_under_threshold_stays_broadcast(self, pq_env,
                                                      monkeypatch):
        monkeypatch.setenv("QK_BROADCAST_BYTES", str(1 << 20))
        joins, log = self._optimize(
            pq_env, monkeypatch, _AnySig({"rows": 100, "bytes": 800}))
        assert joins and joins[0].broadcast
        rec = [d for d in log if d["kind"] == "broadcast"][0]
        assert rec["basis"] == cost.BASIS_MEASURED
        assert rec["choice"] == "broadcast"


# -- the TPC-H flip: a recorded cardprofile flips Q3's orders build -----------


@pytest.fixture(scope="module")
def q3_paths(tmp_path_factory):
    root = tmp_path_factory.mktemp("planner_q3")
    tables = tpch_data.generate(sf=0.01, seed=7)
    # cluster orders by o_orderdate: the catalog's head-rows sample then
    # only ever sees the earliest dates, so a late-date predicate samples
    # near zero rows while actually keeping a large slice of the table —
    # the classic misestimate only a measured profile corrects
    orders = tables["orders"].sort_by([("o_orderdate", "ascending")])
    paths = {}
    for name, table in (("lineitem", tables["lineitem"]),
                        ("orders", orders),
                        ("customer", tables["customer"])):
        p = str(root / f"{name}.parquet")
        pq.write_table(table, p, row_group_size=4096)
        paths[name] = p
    return paths


def _q3(ctx, paths):
    lineitem = ctx.read_parquet(
        paths["lineitem"],
        columns=["l_orderkey", "l_extendedprice", "l_discount"])
    orders = ctx.read_parquet(
        paths["orders"],
        columns=["o_orderkey", "o_custkey", "o_orderdate"],
    ).filter(col("o_orderdate") >= date("1996-01-01"))
    customer = ctx.read_parquet(
        paths["customer"], columns=["c_custkey", "c_mktsegment"],
    ).filter(col("c_mktsegment") == "BUILDING")
    return (
        lineitem.join(orders, left_on="l_orderkey", right_on="o_orderkey")
        .join(customer, left_on="o_custkey", right_on="c_custkey")
        .groupby("l_orderkey")
        .agg_sql("sum(l_extendedprice * (1 - l_discount)) as revenue, "
                 "count(*) as n")
    )


def _orders_broadcast_decision(snap):
    return [d for d in (snap or {}).get("planner") or []
            if d.get("kind") == "broadcast" and "o_orderkey" in d["node"]]


class TestTPCHQ3Flip:
    def test_recorded_profile_flips_orders_build(self, q3_paths, tmp_path,
                                                 monkeypatch):
        from quokka_tpu.service import QueryService

        monkeypatch.setenv("QK_CARDPROFILE_DIR", str(tmp_path))
        monkeypatch.setenv("QK_MEMPROFILE_DIR", "")
        monkeypatch.setenv("QK_BROADCAST_BYTES", str(1 << 16))
        with QueryService(pool_size=2) as svc:
            h = svc.submit(_q3(QuokkaContext(), q3_paths))
            cold_t = h.to_arrow(timeout=300)
            cold_snap = h.explain(as_dict=True)
            h = svc.submit(_q3(QuokkaContext(), q3_paths))
            warm_t = h.to_arrow(timeout=300)
            warm_snap = h.explain(as_dict=True)
            warm_text = h.explain()
        cold = _orders_broadcast_decision(cold_snap)
        assert cold, cold_snap.get("planner")
        assert cold[0]["choice"] == "broadcast"
        assert cold[0]["basis"] != cost.BASIS_MEASURED
        warm = _orders_broadcast_decision(warm_snap)
        assert warm, warm_snap.get("planner")
        assert warm[0]["basis"] == cost.BASIS_MEASURED
        assert warm[0]["choice"] == "partition"
        assert warm[0]["build_bytes"] > warm[0]["threshold_bytes"]
        assert "planner decisions:" in warm_text
        assert "basis=measured" in warm_text
        # the flip trades shuffle topology, never the answer
        cs = cold_t.sort_by("l_orderkey")
        ws = warm_t.sort_by("l_orderkey")
        assert cs["l_orderkey"].equals(ws["l_orderkey"])
        assert cs["n"].equals(ws["n"])
        assert np.allclose(cs["revenue"].to_numpy(),
                           ws["revenue"].to_numpy(), rtol=1e-9)


# -- QK026: adaptive-exchange legality ----------------------------------------


def _armed_plan(pq_env, monkeypatch):
    monkeypatch.setenv("QK_BROADCAST_BYTES", "1")
    monkeypatch.setattr(optimizer, "BROADCAST_THRESHOLD", 0)
    fp, dp = pq_env
    ctx = QuokkaContext()
    q = ctx.read_parquet(fp).join(ctx.read_parquet(dp),
                                  left_on="fk", right_on="pk")
    sub, sid = _subplan(q)
    optimizer.optimize(sub, sid)
    joins = _joins(sub, sid)
    assert joins and getattr(joins[0], "adapt_salt", False), \
        "eligibility pass should arm the inner exchange join"
    return sub, sid, joins[0]


def _qk026_rules(sub, sid):
    return {v.rule for v in planck.collect(sub, sid)
            if v.rule == "QK026"}


class TestQK026:
    def test_armed_inner_join_is_clean(self, pq_env, monkeypatch):
        sub, sid, _ = _armed_plan(pq_env, monkeypatch)
        assert not _qk026_rules(sub, sid)

    def test_left_join_flagged(self, pq_env, monkeypatch):
        sub, sid, join = _armed_plan(pq_env, monkeypatch)
        join.how = "left"
        assert _qk026_rules(sub, sid)

    def test_broadcast_join_flagged(self, pq_env, monkeypatch):
        sub, sid, join = _armed_plan(pq_env, monkeypatch)
        join.broadcast = True
        assert _qk026_rules(sub, sid)

    def test_ordered_join_flagged(self, pq_env, monkeypatch):
        sub, sid, join = _armed_plan(pq_env, monkeypatch)
        join.sorted_by = ["fk"]
        assert _qk026_rules(sub, sid)

    def test_salt_column_reserved(self, pq_env, monkeypatch):
        sub, sid, join = _armed_plan(pq_env, monkeypatch)
        join.schema = list(join.schema) + [decide.SALT_COLUMN]
        assert _qk026_rules(sub, sid)

    def test_adapt_off_never_arms(self, pq_env, monkeypatch):
        monkeypatch.setenv("QK_ADAPT", "0")
        monkeypatch.setenv("QK_BROADCAST_BYTES", "1")
        monkeypatch.setattr(optimizer, "BROADCAST_THRESHOLD", 0)
        fp, dp = pq_env
        ctx = QuokkaContext()
        q = ctx.read_parquet(fp).join(ctx.read_parquet(dp),
                                      left_on="fk", right_on="pk")
        sub, sid = _subplan(q)
        optimizer.optimize(sub, sid)
        assert not any(getattr(j, "adapt_salt", False)
                       for j in _joins(sub, sid))
