"""Wide (two-limb int64) correctness without x64 — the TPU configuration.

The main suite runs with x64 on, so the limb code paths (sort keys, range
filters, asof times, window assignment) are only exercised here.  Every test
flips x64 off, runs values that straddle a 2**31 low-limb boundary (where the
old encoding was non-monotonic, ADVICE r1), and compares against numpy/pandas
oracles on true int64.
"""

import jax
import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from quokka_tpu import QuokkaContext
from quokka_tpu.ops import asof as asof_ops
from quokka_tpu.ops import bridge, kernels, timewide
from quokka_tpu.windows import TumblingWindow


@pytest.fixture
def no_x64():
    jax.config.update("jax_enable_x64", False)
    try:
        yield
    finally:
        jax.config.update("jax_enable_x64", True)


def straddling_values(seed=7, n=512):
    """int64 values whose low 32 bits cluster around 2**31 (both sides), with
    several distinct high limbs including negatives."""
    r = np.random.default_rng(seed)
    his = np.array([-2, -1, 0, 1, 5], dtype=np.int64)
    hi = his[r.integers(0, len(his), n)] << np.int64(32)
    lo = (2**31 + r.integers(-1000, 1000, n)).astype(np.int64) % (2**32)
    extra = r.integers(0, 2**32, n).astype(np.uint64).astype(np.int64)
    vals = np.where(r.random(n) < 0.5, hi + lo, hi + extra)
    return vals


class TestLimbEncoding:
    def test_roundtrip_arrow(self, no_x64):
        vals = straddling_values()
        t = pa.table({"x": vals})
        b = bridge.arrow_to_device(t)
        assert b.columns["x"].hi is not None  # actually exercising limbs
        back = bridge.device_to_arrow(b)
        np.testing.assert_array_equal(back.column("x").to_numpy(), vals)

    def test_sort_straddles_lo_boundary(self, no_x64):
        vals = straddling_values()
        b = bridge.arrow_to_device(pa.table({"x": vals}))
        s = kernels.sort_batch(b, ["x"])
        got = bridge.device_to_arrow(s).column("x").to_numpy()
        np.testing.assert_array_equal(got, np.sort(vals))

    def test_rebase_roundtrip(self, no_x64):
        r = np.random.default_rng(11)
        # wide absolute values, span < 2**31, crossing a low-limb wrap
        vals = 1_600_000_000_000_000_000 + r.integers(0, 2**31 - 2048, 512)
        base = int(vals.min()) - 123
        b = bridge.arrow_to_device(pa.table({"x": vals}))
        col = b.columns["x"]
        np.testing.assert_array_equal(timewide.host_i64(col, b.valid), vals)
        rel = timewide.rebase_narrow(col, b.valid, base)
        restored = timewide.add_base(rel.data, base, "i", None)
        got = timewide.host_i64(restored, b.valid)
        np.testing.assert_array_equal(got, vals)

    def test_rebase_overflow_raises(self, no_x64):
        vals = np.array([0, 2**33], dtype=np.int64)
        b = bridge.arrow_to_device(pa.table({"x": vals}))
        with pytest.raises(ValueError, match="coarser unit"):
            timewide.rebase_narrow(b.columns["x"], b.valid, 0)

    def test_range_partition_counts(self, no_x64):
        vals = straddling_values(seed=13)
        bounds = sorted(int(v) for v in straddling_values(seed=17, n=7))
        b = bridge.arrow_to_device(pa.table({"x": vals}))
        got = np.asarray(timewide.limb_le_scalar_count(b.columns["x"], bounds))
        exp = np.searchsorted(np.array(bounds), vals, side="right")
        np.testing.assert_array_equal(got[: len(vals)], exp)


class TestMixedLimbConcat:
    """A stream legitimately mixes plain-int32 and two-limb batches for the
    same int64 column (_ints_to_col decides per batch from the value range);
    concat must promote, not drop limbs (r2 code review)."""

    def _batches(self):
        small = pa.table({"x": np.array([5, -3, 7, 0], dtype=np.int64)})
        wide = pa.table({"x": straddling_values(n=256)})
        bs = bridge.arrow_to_device(small)
        bw = bridge.arrow_to_device(wide)
        assert bs.columns["x"].hi is None and bw.columns["x"].hi is not None
        exp = np.concatenate(
            [small.column("x").to_numpy(), wide.column("x").to_numpy()]
        )
        return bs, bw, exp

    def test_compacting_concat_promotes(self, no_x64):
        bs, bw, exp = self._batches()
        out = bridge.concat_batches([bs, bw])
        got = bridge.device_to_arrow(out).column("x").to_numpy()
        np.testing.assert_array_equal(got, exp)

    def test_device_concat_promotes(self, no_x64):
        bs, bw, exp = self._batches()
        # unknown nrows routes through the sync-free device concat
        bs.nrows = None
        bs.nrows_dev = None
        bw.nrows = None
        bw.nrows_dev = None
        out = bridge._concat_batches_device([bs, bw])
        got = bridge.device_to_arrow(out).column("x").to_numpy()
        np.testing.assert_array_equal(got, exp)

    def test_null_sentinel_survives_promotion(self, no_x64):
        small = pa.table({"x": pa.array([5, None, 7], type=pa.int64())})
        wide = pa.table({"x": straddling_values(n=256)})
        bs = bridge.arrow_to_device(small)
        bw = bridge.arrow_to_device(wide)
        out = bridge.concat_batches([bs, bw])
        got = bridge.device_to_arrow(out).column("x")
        assert got.null_count == 1
        assert got.to_pylist()[1] is None


class TestWideQueries:
    def test_filter_and_sort_query(self, no_x64):
        vals = straddling_values(seed=23)
        bound = int(np.median(vals))
        t = pa.table({"x": vals, "v": np.arange(len(vals), dtype=np.int32)})
        ctx = QuokkaContext()
        got = (
            ctx.from_arrow(t)
            .filter_sql(f"x > {bound}")
            .sort("x")
            .collect()
        )
        exp = t.to_pandas().query("x > @bound")
        assert (np.diff(got["x"].to_numpy()) >= 0).all()  # engine output x-ordered
        # duplicate x values: engine sort is by x only, so tiebreak both sides
        got = got.sort_values(["x", "v"]).reset_index(drop=True)
        exp = exp.sort_values(["x", "v"]).reset_index(drop=True)
        np.testing.assert_array_equal(got["x"].to_numpy(), exp["x"].to_numpy())
        np.testing.assert_array_equal(got["v"].to_numpy(), exp["v"].to_numpy())


def make_wide_ticks(seed=5, n_trades=600, n_quotes=1200):
    """Tick times as ns-scale int64 spanning multiple 2**32 boundaries."""
    r = np.random.default_rng(seed)
    base = 1_600_000_000_000_000_000  # ~2020 in ns
    span = 40_000_000_000  # 40s in ns: ~9 low-limb wraps
    tt = base + np.sort(r.integers(0, span, n_trades))
    qt = base + np.sort(r.choice(span, n_quotes, replace=False))
    syms = np.array(["A", "B", "C"])
    trades = pa.table(
        {"time": tt, "symbol": syms[r.integers(0, 3, n_trades)],
         "size": r.integers(1, 100, n_trades).astype(np.int32)}
    )
    quotes = pa.table(
        {"time": qt, "symbol": syms[r.integers(0, 3, n_quotes)],
         "bid": r.uniform(10, 20, n_quotes).round(2).astype(np.float32)}
    )
    return trades, quotes


class TestWideTimeseries:
    def test_asof_kernel_backward_and_forward(self, no_x64):
        trades, quotes = make_wide_ticks()
        tb = bridge.arrow_to_device(trades)
        qb = bridge.arrow_to_device(quotes)
        assert tb.columns["time"].hi is not None
        for direction in ("backward", "forward"):
            out = asof_ops.asof_join(
                tb, qb, "time", "time", ["symbol"], ["symbol"], ["bid"],
                direction=direction,
            )
            out = kernels.apply_mask(out, out.columns.pop("__asof_matched__").data)
            got = bridge.device_to_arrow(kernels.compact(out)).to_pandas()
            exp = pd.merge_asof(
                trades.to_pandas(), quotes.to_pandas(), on="time",
                by="symbol", direction=direction,
            ).dropna(subset=["bid"])
            got = got.sort_values(["time", "symbol"]).reset_index(drop=True)
            exp = exp.sort_values(["time", "symbol"]).reset_index(drop=True)
            assert len(got) == len(exp), direction
            np.testing.assert_allclose(
                got.bid.to_numpy(), exp.bid.to_numpy(), rtol=1e-6
            )

    def test_streaming_asof_wide(self, no_x64):
        trades, quotes = make_wide_ticks(seed=9)
        ctx = QuokkaContext()
        t = ctx.from_arrow_sorted(trades, sorted_by="time")
        q = ctx.from_arrow_sorted(quotes, sorted_by="time")
        got = t.join_asof(q, on="time", by="symbol").collect()
        exp = pd.merge_asof(
            trades.to_pandas(), quotes.to_pandas(), on="time",
            by="symbol", direction="backward",
        ).dropna(subset=["bid"])
        got = got.sort_values(["time", "symbol"]).reset_index(drop=True)
        exp = exp.sort_values(["time", "symbol"]).reset_index(drop=True)
        assert len(got) == len(exp)
        np.testing.assert_array_equal(got.time.to_numpy(), exp.time.to_numpy())
        np.testing.assert_allclose(got.bid.to_numpy(), exp.bid.to_numpy(), rtol=1e-6)

    def test_streaming_asof_forward(self, no_x64):
        trades, quotes = make_wide_ticks(seed=13)
        ctx = QuokkaContext()
        t = ctx.from_arrow_sorted(trades, sorted_by="time")
        q = ctx.from_arrow_sorted(quotes, sorted_by="time")
        got = t.join_asof(q, on="time", by="symbol", direction="forward").collect()
        exp = pd.merge_asof(
            trades.to_pandas(), quotes.to_pandas(), on="time",
            by="symbol", direction="forward",
        ).dropna(subset=["bid"])
        got = got.sort_values(["time", "symbol"]).reset_index(drop=True)
        exp = exp.sort_values(["time", "symbol"]).reset_index(drop=True)
        assert len(got) == len(exp)
        np.testing.assert_allclose(got.bid.to_numpy(), exp.bid.to_numpy(), rtol=1e-6)

    def test_tumbling_window_wide_ns(self, no_x64):
        # rebase path: span must fit int32 units; put the base just below a
        # 2**32 wrap so window times still cross a low-limb boundary
        r = np.random.default_rng(21)
        k = 1_600_000_000_000_000_000 // 2**32
        base = (k + 1) * 2**32 - 900_000_000
        tt = base + np.sort(r.integers(0, 1_000_000_000, 600))
        syms = np.array(["A", "B", "C"])
        trades = pa.table(
            {"time": tt, "symbol": syms[r.integers(0, 3, 600)],
             "size": r.integers(1, 100, 600).astype(np.int32)}
        )
        size = 200_000_000
        ctx = QuokkaContext()
        s = ctx.from_arrow_sorted(trades, sorted_by="time")
        got = s.window_agg(
            TumblingWindow(size), "sum(size) as vol", by="symbol"
        ).collect()
        df = trades.to_pandas()
        df["window_start"] = (df.time // size) * size
        exp = (
            df.groupby(["symbol", "window_start"])["size"].sum().reset_index(name="vol")
        )
        got = got.sort_values(["symbol", "window_start"]).reset_index(drop=True)
        exp = exp.sort_values(["symbol", "window_start"]).reset_index(drop=True)
        np.testing.assert_array_equal(
            got.window_start.to_numpy().astype(np.int64), exp.window_start.to_numpy()
        )
        np.testing.assert_allclose(got.vol.to_numpy(), exp.vol.to_numpy(), rtol=1e-6)
