"""SQL frontend tests: SELECT over registered tables."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from quokka_tpu import QuokkaContext


@pytest.fixture
def env(table, pdf):
    ctx = QuokkaContext()
    ctx.register("t", ctx.from_arrow(table))
    r = np.random.default_rng(1)
    dim = pa.table(
        {"k": np.arange(20, dtype=np.int64), "label": [f"L{i%4}" for i in range(20)]}
    )
    ctx.register("dim", ctx.from_arrow(dim))
    return ctx, pdf, dim.to_pandas()


class TestSql:
    def test_projection_where(self, env):
        ctx, pdf, _ = env
        got = ctx.sql("select k, v * 2 as v2 from t where q > 25").collect()
        exp = pdf[pdf.q > 25]
        assert len(got) == len(exp)
        np.testing.assert_allclose(sorted(got.v2), sorted(exp.v * 2))

    def test_group_by_having_order(self, env):
        ctx, pdf, _ = env
        got = ctx.sql(
            "select k, sum(v) as sv, count(*) as n from t "
            "group by k having count(*) > 30 order by k"
        ).collect()
        exp = (
            pdf.groupby("k")
            .agg(sv=("v", "sum"), n=("v", "size"))
            .reset_index()
        )
        exp = exp[exp.n > 30].reset_index(drop=True)
        pd.testing.assert_frame_equal(got, exp, check_dtype=False, rtol=1e-9)

    def test_join(self, env):
        ctx, pdf, dimdf = env
        got = ctx.sql(
            "select label, count(*) as n from t join dim on k = k "
            "group by label order by label"
        ).collect()
        exp = (
            pdf.merge(dimdf, on="k").groupby("label").size().reset_index(name="n")
        )
        pd.testing.assert_frame_equal(got, exp, check_dtype=False)

    def test_distinct_limit(self, env):
        ctx, pdf, _ = env
        got = ctx.sql("select distinct s from t").collect()
        assert set(got.s) == set(pdf.s)
        got = ctx.sql("select k from t order by k desc limit 3").collect()
        assert got.k.tolist() == sorted(pdf.k, reverse=True)[:3]

    def test_agg_schema_matches_select_list(self, env):
        ctx, pdf, _ = env
        got = ctx.sql("select count(*) as n from t group by k").collect()
        assert list(got.columns) == ["n"]  # group key NOT auto-included
        got = ctx.sql("select k as kk, sum(v) as sv from t group by k order by kk").collect()
        assert list(got.columns) == ["kk", "sv"]
        exp = pdf.groupby("k").v.sum().sort_index()
        np.testing.assert_allclose(got.sv.to_numpy(), exp.to_numpy())

    def test_unknown_table(self, env):
        ctx, _, _ = env
        with pytest.raises(ValueError, match="unknown table"):
            ctx.sql("select x from nope")


class TestRegressions:
    def test_statement_words_as_column_names(self):
        # `left`, `order`, `on`, `limit` must keep working as column names in
        # the expression surfaces (regression: SELECT keywords broke them)
        from quokka_tpu import sqlparse

        e = sqlparse.parse_expression("left > 1 and limit < 5")
        assert e.required_columns() == {"left", "limit"}
        ctx = QuokkaContext()
        t = pa.table({"left": np.arange(10, dtype=np.int64),
                      "order": np.arange(10, dtype=np.float64)})
        got = ctx.from_arrow(t).filter_sql("left > 6").collect()
        assert len(got) == 3

    def test_group_limit_without_order_is_global(self, env):
        ctx, pdf, _ = env
        got = ctx.sql("select k, sum(v) as sv from t group by k limit 3").collect()
        assert len(got) == 3  # regression: per-channel limit returned 2x

    def test_covariance_multi_channel(self):
        from quokka_tpu.dataset.readers import InputArrowDataset

        r = np.random.default_rng(9)
        n = 4000
        t = pa.table({"v": r.normal(size=n), "q": r.normal(size=n) * 2})
        ctx = QuokkaContext(exec_channels=2)
        s = ctx.read_dataset(InputArrowDataset(t, batch_rows=256))
        got = s.covariance(["v", "q"]).collect()
        X = t.to_pandas()[["v", "q"]].to_numpy()
        exp = np.cov(X.T, bias=True)
        gm = got.set_index("column").loc[["v", "q"], ["v", "q"]].to_numpy()
        np.testing.assert_allclose(gm, exp, rtol=1e-3, atol=1e-4)
