"""Placement strategies (reference placement_strategy.py:8-36) and the
spill-backed PersistentStateVariable (reference state.py:6)."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from quokka_tpu import (
    CustomChannelsStrategy,
    DatasetStrategy,
    QuokkaContext,
    SingleChannelStrategy,
    TaggedCustomChannelsStrategy,
)
from quokka_tpu.runtime.placement import assign_channels
from quokka_tpu.runtime.state import PersistentStateVariable
from quokka_tpu.utils.cluster import LocalCluster


class FakeActor:
    def __init__(self, aid, channels, placement=None):
        self.id = aid
        self.channels = channels
        self.placement = placement


class TestAssignment:
    def test_single_channel_pins_worker_zero(self):
        owned = assign_channels({0: FakeActor(0, 1, SingleChannelStrategy())}, 3)
        assert owned[0] == {0: [0]} and not owned[1] and not owned[2]

    def test_custom_channels_spread(self):
        a = FakeActor(0, 4, CustomChannelsStrategy(2))
        owned = assign_channels({0: a}, 2)
        assert owned[0][0] == [0, 1] and owned[1][0] == [2, 3]

    def test_tagged_restricts_to_tagged_workers(self):
        strat = TaggedCustomChannelsStrategy(1, tag="tpu")
        a = FakeActor(0, 2, strat)
        tags = {0: set(), 1: {"tpu"}, 2: {"tpu"}}
        owned = assign_channels({0: a}, 3, tags)
        assert not owned[0]
        assert owned[1][0] == [0] and owned[2][0] == [1]

    def test_tagged_without_tagged_worker_raises(self):
        strat = TaggedCustomChannelsStrategy(1, tag="tpu")
        with pytest.raises(ValueError, match="tag"):
            assign_channels({0: FakeActor(0, 1, strat)}, 2, {0: set(), 1: set()})

    def test_dataset_one_channel_per_worker(self):
        owned = assign_channels({0: FakeActor(0, 2, DatasetStrategy())}, 2)
        assert owned[0][0] == [0] and owned[1][0] == [1]

    def test_unplaced_round_robin_alongside_placed(self):
        actors = {
            0: FakeActor(0, 3),
            1: FakeActor(1, 1, SingleChannelStrategy()),
        }
        owned = assign_channels(actors, 2)
        assert owned[0][0] == [0, 2] and owned[1][0] == [1]
        assert owned[0][1] == [0]

    def test_num_channels(self):
        assert SingleChannelStrategy().num_channels(4, 2) == 1
        assert CustomChannelsStrategy(3).num_channels(4, 2) == 12
        assert DatasetStrategy().num_channels(4, 2) == 4
        t = TaggedCustomChannelsStrategy(2, tag="io")
        assert t.num_channels(4, 2) == 8  # tags unknown: every worker
        assert t.num_channels(4, 2, {0: {"io"}, 1: set()}) == 2


class SummingExecutor:
    """Minimal user executor: running per-channel sum, emitted at done."""

    def __init__(self):
        self.total = 0.0
        self.count = 0

    def execute(self, batches, stream_id, channel):
        from quokka_tpu.ops import bridge

        for b in batches:
            df = bridge.device_to_arrow(b).to_pandas()
            self.total += float(df.v.sum())
            self.count += len(df)
        return None

    def done(self, channel):
        from quokka_tpu.ops import bridge

        return bridge.arrow_to_device(
            pa.table({"total": [self.total], "n": [self.count]})
        )

    def source_done(self, stream_id, channel):
        return None


class TestPlacedQuery:
    def _data(self):
        r = np.random.default_rng(7)
        return pa.table({"v": r.uniform(0, 10, 5000).round(3)})

    def test_single_channel_stateful_transform_embedded(self):
        ctx = QuokkaContext()
        t = self._data()
        got = (
            ctx.from_arrow(t)
            .stateful_transform(
                SummingExecutor(), ["total", "n"],
                placement=SingleChannelStrategy(),
            )
            .collect()
        )
        assert len(got) == 1
        np.testing.assert_allclose(
            got.total.iloc[0], t.to_pandas().v.sum(), rtol=1e-9
        )
        assert got.n.iloc[0] == 5000

    def test_single_channel_stateful_transform_two_workers(self, monkeypatch):
        t = self._data()

        # Regression guard for the round-5 600s hang (Worker crashed on its
        # first dispatch because PR5's _lat_hist was never initialized —
        # Worker bypasses Engine.__init__; fixed by the shared
        # _init_latency_hists).  A healthy run finishes in seconds; if the
        # coordinator ever wedges again, the QK_COORD_TIMEOUT stall
        # detector shoots it in ~60s WITH a merged-timeline stall dump
        # naming the stuck worker, instead of 600s of silence.
        monkeypatch.setenv("QK_COORD_TIMEOUT", "60")

        def run(ctx):
            return (
                ctx.from_arrow(t)
                .stateful_transform(
                    SummingExecutor(), ["total", "n"],
                    placement=SingleChannelStrategy(),
                )
                .collect()
            )

        got = run(QuokkaContext(cluster=LocalCluster(n_workers=2)))
        assert len(got) == 1
        np.testing.assert_allclose(
            got.total.iloc[0], t.to_pandas().v.sum(), rtol=1e-9
        )
        # the CLT must have pinned the placed actor's only channel to worker 0
        # (SingleChannelStrategy semantics)


class TestPersistentStateVariable:
    def _table(self, n=1000, seed=0):
        r = np.random.default_rng(seed)
        return pa.table({"x": r.integers(0, 100, n), "y": r.uniform(0, 1, n)})

    def test_in_memory_roundtrip(self):
        psv = PersistentStateVariable(mem_limit_bytes=1 << 30)
        t1, t2 = self._table(seed=1), self._table(seed=2)
        psv.append(t1)
        psv.append(t2)
        assert len(psv) == 2
        out = psv.to_table()
        assert out.num_rows == 2000
        pd.testing.assert_frame_equal(
            out.to_pandas(), pa.concat_tables([t1, t2]).to_pandas()
        )

    def test_spills_past_cap_and_streams_back(self, tmp_path):
        t = self._table(n=5000)
        psv = PersistentStateVariable(
            mem_limit_bytes=t.nbytes + 100, spill_dir=str(tmp_path)
        )
        tables = [self._table(n=5000, seed=s) for s in range(4)]
        for x in tables:
            psv.append(x)
        import os

        assert psv._spill_files, "expected spill files past the cap"
        assert all(os.path.exists(p) for p in psv._spill_files)
        got = psv.to_table().to_pandas()
        exp = pa.concat_tables(tables).to_pandas()
        # spill preserves append order: spilled prefix first, memory tail last
        pd.testing.assert_frame_equal(
            got.sort_values(["x", "y"]).reset_index(drop=True),
            exp.sort_values(["x", "y"]).reset_index(drop=True),
        )
        assert psv.num_rows() == 20000
        psv.clear()
        assert len(psv) == 0 and psv.to_table() is None

    def test_oversized_single_table_spills_directly(self, tmp_path):
        t = self._table(n=5000)
        psv = PersistentStateVariable(mem_limit_bytes=100, spill_dir=str(tmp_path))
        psv.append(t)
        assert psv._spill_files and not psv._mem
        assert psv.to_table().num_rows == 5000
