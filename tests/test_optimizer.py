"""Optimizer pass tests: pushdown reaches readers, projection prunes columns,
broadcast selection fires, and optimized plans stay correct vs unoptimized."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from quokka_tpu import QuokkaContext, col, date, logical
from quokka_tpu.optimizer import optimize


@pytest.fixture(scope="module")
def pq_env(tmp_path_factory):
    root = tmp_path_factory.mktemp("opt")
    r = np.random.default_rng(11)
    n = 20_000
    fact = pa.table(
        {
            "k": r.integers(0, 100, n).astype(np.int64),
            "x": r.normal(size=n),
            "big": [f"payload-{i}" for i in range(n)],  # should get pruned
            "d": pa.array(r.integers(8000, 12000, n).astype(np.int32), type=pa.int32()).cast(
                pa.date32()
            ),
        }
    )
    dim = pa.table(
        {
            "k": np.arange(100, dtype=np.int64),
            "name": [f"n{i}" for i in range(100)],
        }
    )
    fp, dp = str(root / "fact.parquet"), str(root / "dim.parquet")
    pq.write_table(fact, fp, row_group_size=2048)
    pq.write_table(dim, dp)
    return fp, dp, fact.to_pandas(), dim.to_pandas()


def optimized_plan(stream):
    ctx = stream.ctx
    sub, _ = ctx._copy_subgraph(stream.node_id)
    sink = logical.SinkNode([stream.node_id], sub[stream.node_id].schema)
    sid = max(sub) + 1
    sub[sid] = sink
    optimize(sub, sid)
    return sub, sid


def find_nodes(sub, sid, cls):
    from quokka_tpu.optimizer import _reachable

    return [sub[n] for n in _reachable(sub, sid) if isinstance(sub[n], cls)]


class TestPushdown:
    def test_filter_reaches_source(self, pq_env):
        fp, dp, fdf, ddf = pq_env
        ctx = QuokkaContext()
        q = ctx.read_parquet(fp).filter(col("k") > 50).filter(col("x") > 0)
        sub, sid = optimized_plan(q)
        srcs = find_nodes(sub, sid, logical.SourceNode)
        assert len(srcs) == 1
        assert srcs[0].predicate is not None
        assert not find_nodes(sub, sid, logical.FilterNode)

    def test_filter_pushes_through_join(self, pq_env):
        fp, dp, fdf, ddf = pq_env
        ctx = QuokkaContext()
        f = ctx.read_parquet(fp)
        d = ctx.read_parquet(dp)
        q = f.join(d, on="k", suffix="_r").filter(col("x") > 1.0)
        sub, sid = optimized_plan(q)
        srcs = find_nodes(sub, sid, logical.SourceNode)
        fact_src = [s for s in srcs if "x" in s.schema][0]
        assert fact_src.predicate is not None and "x" in fact_src.predicate.sql()

    def test_pushdown_correctness(self, pq_env):
        fp, dp, fdf, ddf = pq_env
        for opt in (True, False):
            ctx = QuokkaContext(optimize=opt)
            got = (
                ctx.read_parquet(fp)
                .join(ctx.read_parquet(dp), on="k")
                .filter(col("x") > 1.0)
                .groupby("name")
                .agg_sql("count(*) as n, sum(x) as sx")
                .collect()
            )
            m = fdf[fdf.x > 1.0].merge(ddf, on="k")
            exp = m.groupby("name").agg(n=("x", "size"), sx=("x", "sum")).reset_index()
            got = got.sort_values("name").reset_index(drop=True)
            exp = exp.sort_values("name").reset_index(drop=True)
            pd.testing.assert_frame_equal(got, exp, check_dtype=False, rtol=1e-9)

    def test_rowgroup_pruning_happens(self, pq_env):
        fp, dp, fdf, ddf = pq_env
        ctx = QuokkaContext()
        # d > all data -> every row group pruned -> zero rows, fast
        got = ctx.read_parquet(fp).filter(col("d") > date("2200-01-01")).count()
        assert got == 0
        g = ctx.latest_graph
        src = [a for a in g.actors.values() if a.kind == "input"][0]
        n_pieces = sum(
            len(v) for v in src.reader.get_own_state(1).values()
        )
        assert n_pieces == 0  # all row groups excluded by min/max stats


class TestProjection:
    def test_source_prunes_columns(self, pq_env):
        fp, dp, fdf, ddf = pq_env
        ctx = QuokkaContext()
        q = (
            ctx.read_parquet(fp)
            .filter(col("k") > 10)
            .groupby("k")
            .agg_sql("sum(x) as sx")
        )
        sub, sid = optimized_plan(q)
        src = find_nodes(sub, sid, logical.SourceNode)[0]
        assert src.projection is not None
        assert "big" not in src.projection
        assert "x" in src.projection and "k" in src.projection

    def test_projection_correctness(self, pq_env):
        fp, dp, fdf, ddf = pq_env
        ctx = QuokkaContext()
        got = (
            ctx.read_parquet(fp)
            .filter(col("k") > 10)
            .groupby("k")
            .agg_sql("sum(x) as sx")
            .collect()
        )
        exp = fdf[fdf.k > 10].groupby("k").x.sum().reset_index(name="sx")
        got = got.sort_values("k").reset_index(drop=True)
        pd.testing.assert_frame_equal(got, exp, check_dtype=False, rtol=1e-9)


class TestJoinSuffixUnderProjection:
    def test_pruned_clash_column_keeps_planned_suffix(self):
        # left(a, c) join right(k, c): selecting only the RIGHT c (c_2) prunes
        # the left c; the planned rename must still apply (regression: the
        # runtime collision detection used to emit 'c' and crash the select)
        ctx = QuokkaContext()
        left = pa.table({"a": np.arange(10, dtype=np.int64),
                         "c": np.arange(10, dtype=np.float64)})
        right = pa.table({"k": np.arange(10, dtype=np.int64),
                          "c": np.arange(10, dtype=np.float64) * 10})
        got = (
            ctx.from_arrow(left)
            .join(ctx.from_arrow(right), left_on="a", right_on="k")
            .select(["a", "c_2"])
            .collect()
        )
        got = got.sort_values("a").reset_index(drop=True)
        np.testing.assert_allclose(got.c_2.to_numpy(), np.arange(10) * 10.0)


class TestBroadcast:
    def test_small_build_becomes_broadcast(self, pq_env):
        fp, dp, fdf, ddf = pq_env
        ctx = QuokkaContext()
        q = ctx.read_parquet(fp).join(ctx.read_parquet(dp), on="k")
        sub, sid = optimized_plan(q)
        joins = find_nodes(sub, sid, logical.JoinNode)
        assert len(joins) == 1
        assert joins[0].broadcast  # dim is 100 rows < threshold

    def test_broadcast_correctness(self, pq_env):
        fp, dp, fdf, ddf = pq_env
        ctx = QuokkaContext()
        got = ctx.read_parquet(fp).join(ctx.read_parquet(dp), on="k").count()
        exp = len(fdf.merge(ddf, on="k"))
        assert got == exp


class TestParallelSort:
    def test_sort_becomes_range_partitioned(self, pq_env):
        fp, dp, fdf, ddf = pq_env
        ctx = QuokkaContext(exec_channels=2)
        q = ctx.read_parquet(fp).sort(["x"])
        sub, sid = optimized_plan(q)
        sorts = find_nodes(sub, sid, logical.SortNode)
        assert len(sorts) == 1 and sorts[0].boundaries is not None
        assert len(sorts[0].boundaries) == 1  # n_channels - 1

    def test_parallel_sort_correct(self, pq_env):
        fp, dp, fdf, ddf = pq_env
        for desc in (False, True):
            ctx = QuokkaContext(exec_channels=2)
            got = ctx.read_parquet(fp).sort(["x"], [desc]).collect()
            exp = fdf.sort_values("x", ascending=not desc).reset_index(drop=True)
            np.testing.assert_allclose(got.x.to_numpy(), exp.x.to_numpy())

    def test_parallel_sort_with_filter(self, pq_env):
        fp, dp, fdf, ddf = pq_env
        ctx = QuokkaContext(exec_channels=2)
        got = ctx.read_parquet(fp).filter(col("k") > 50).sort(["x"]).collect()
        exp = fdf[fdf.k > 50].sort_values("x").reset_index(drop=True)
        np.testing.assert_allclose(got.x.to_numpy(), exp.x.to_numpy())

    def test_sort_then_chain_stays_ordered(self, pq_env):
        # regression: ops chained after a parallel sort must preserve order
        fp, dp, fdf, ddf = pq_env
        ctx = QuokkaContext(exec_channels=2)
        got = ctx.read_parquet(fp).sort(["x"]).select(["x"]).collect()
        np.testing.assert_allclose(got.x.to_numpy(), np.sort(fdf.x.to_numpy()))

    def test_unsampleable_schema_does_not_break_planning(self, tmp_path):
        # a list column the query never touches must not crash the sampler
        t = pa.table(
            {
                "x": np.random.default_rng(0).normal(size=1000),
                "weird": pa.array([[1, 2]] * 1000, type=pa.list_(pa.int64())),
            }
        )
        p = str(tmp_path / "weird.parquet")
        pq.write_table(t, p)
        ctx = QuokkaContext(exec_channels=2)
        got = ctx.read_parquet(p, columns=["x"]).filter(col("x") > 0).sort(["x"]).collect()
        exp = np.sort(t.column("x").to_numpy()[t.column("x").to_numpy() > 0])
        np.testing.assert_allclose(got.x.to_numpy(), exp)


class TestJoinReorderAndFoldMap:
    """VERDICT r1 item 6: cardinality-greedy join reordering + map folding."""

    def _tables(self):
        r = np.random.default_rng(0)
        fact = pa.table({"k1": r.integers(0, 1000, 20000).astype(np.int64),
                         "k2": r.integers(0, 50, 20000).astype(np.int64),
                         "v": r.uniform(0, 1, 20000)})
        big = pa.table({"k1": np.arange(1000, dtype=np.int64),
                        "b1": r.uniform(0, 1, 1000)})
        small = pa.table({"k2": np.arange(50, dtype=np.int64),
                          "s1": r.uniform(0, 1, 50)})
        return fact, big, small

    def test_chain_reordered_smallest_first(self):
        fact, big, small = self._tables()
        ctx = QuokkaContext()
        q = (ctx.from_arrow(fact)
             .join(ctx.from_arrow(big), on="k1")
             .join(ctx.from_arrow(small), on="k2")
             .groupby("k2").agg_sql("sum(v) as s"))
        plan = q.explain()
        # the small (k2) join must appear BELOW the big (k1) join post-reorder
        k2_line = next(i for i, l in enumerate(plan.splitlines()) if "['k2']=['k2']" in l)
        k1_line = next(i for i, l in enumerate(plan.splitlines()) if "['k1']=['k1']" in l)
        assert k2_line < k1_line, plan
        got = q.collect().sort_values("k2").reset_index(drop=True)
        df = fact.to_pandas().merge(big.to_pandas(), on="k1").merge(
            small.to_pandas(), on="k2")
        exp = df.groupby("k2").v.sum().reset_index(name="s")
        np.testing.assert_allclose(got.s.to_numpy(), exp.s.to_numpy(), rtol=1e-9)

    def test_snowflake_dependency_respected(self):
        # customer key comes from the orders dim: customer join CANNOT move
        # below the orders join no matter how small customer is
        r = np.random.default_rng(1)
        li = pa.table({"ok": r.integers(0, 500, 10000).astype(np.int64),
                       "v": r.uniform(0, 1, 10000)})
        orders = pa.table({"ok": np.arange(500, dtype=np.int64),
                           "ck": r.integers(0, 20, 500).astype(np.int64)})
        cust = pa.table({"ck": np.arange(20, dtype=np.int64),
                         "seg": np.array(["A", "B"])[np.arange(20) % 2]})
        ctx = QuokkaContext()
        q = (ctx.from_arrow(li)
             .join(ctx.from_arrow(orders), on="ok")
             .join(ctx.from_arrow(cust), on="ck")
             .groupby("seg").agg_sql("sum(v) as s"))
        got = q.collect().sort_values("seg").reset_index(drop=True)
        df = li.to_pandas().merge(orders.to_pandas(), on="ok").merge(
            cust.to_pandas(), on="ck")
        exp = df.groupby("seg").v.sum().reset_index(name="s")
        np.testing.assert_allclose(got.s.to_numpy(), exp.s.to_numpy(), rtol=1e-9)

    def test_fold_map_no_actor_hop(self):
        fact, big, _ = self._tables()
        ctx = QuokkaContext()
        q = (ctx.from_arrow(fact)
             .join(ctx.from_arrow(big), on="k1")
             .with_columns_sql("v * b1 as vb")
             .groupby("k2").agg_sql("sum(vb) as s"))
        plan = q.explain()
        assert "FoldedMap" in plan, plan
        got = q.collect().sort_values("k2").reset_index(drop=True)
        df = fact.to_pandas().merge(big.to_pandas(), on="k1")
        df["vb"] = df.v * df.b1
        exp = df.groupby("k2").vb.sum().reset_index(name="s")
        np.testing.assert_allclose(got.s.to_numpy(), exp.s.to_numpy(), rtol=1e-9)
