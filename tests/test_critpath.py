"""Critical-path profiler: known-answer reconstruction on a hand-built
synthetic trace, wall-clock reconciliation on a real query, and the
flight-recorder drop counter surfacing (ISSUE 5)."""

import io

import numpy as np
import pyarrow as pa
import pytest

from quokka_tpu import QuokkaContext, obs
from quokka_tpu.obs import critpath
from quokka_tpu.obs.recorder import FlightRecorder


def _ev(seq, ts, kind, name, dur=0.0, thread="T", args=None):
    return (seq, ts, kind, name, dur, thread, args)


def _synthetic_stream():
    """One query, three tasks, hand-placed gaps — every bucket knowable.

    input a0c0   [100.0, 100.5]  spans: reader 0.3 + bridge 0.1, rest 0.1
      (0.2 gap: inputs ready, waiting for a slot -> queue_wait)
    exec  a1c0   [100.7, 101.0]  span exec.* 0.25, rest 0.05
      (0.4 gap with a task.wait marker for a2 -> stall)
    exec  a2c0   [101.4, 101.5]  no spans -> other
    """
    q = {"q": "q1"}
    return [
        _ev(0, 100.3, "span", "reader.execute", 0.3),
        _ev(1, 100.4, "span", "bridge.to_device", 0.1),
        _ev(2, 100.5, "task", "q1:input:a0c0", 0.5,
            args={**q, "a": 0, "c": 0, "k": "input", "outs": [0]}),
        _ev(3, 100.95, "span", "exec.GroupAgg", 0.25),
        _ev(4, 101.0, "task", "q1:exec:a1c0", 0.3,
            args={**q, "a": 1, "c": 0, "k": "exec", "src": 0,
                  "in": [[0, 0]], "outs": [0]}),
        _ev(5, 101.2, "task.wait", "q1:exec:a2c0", 0.0,
            args={**q, "a": 2, "c": 0, "k": "exec"}),
        _ev(6, 101.5, "task", "q1:exec:a2c0", 0.1,
            args={**q, "a": 2, "c": 0, "k": "exec", "src": 1,
                  "in": [[0, 0]]}),
    ]


class TestSyntheticKnownAnswer:
    def test_buckets_and_path(self):
        merged = obs.merge_streams({"w0": _synthetic_stream()})
        cp = critpath.analyze(merged)
        assert cp is not None and cp.query == "q1"
        assert [s["label"] for s in cp.path] == [
            "q1:input:a0c0", "q1:exec:a1c0", "q1:exec:a2c0"]
        b = cp.buckets
        assert b["scan_read"] == pytest.approx(0.3, abs=1e-9)
        assert b["transfer"] == pytest.approx(0.1, abs=1e-9)
        assert b["compute"] == pytest.approx(0.25, abs=1e-9)
        assert b["queue_wait"] == pytest.approx(0.2, abs=1e-9)
        assert b["stall"] == pytest.approx(0.4, abs=1e-9)  # task.wait gap
        assert b["other"] == pytest.approx(0.25, abs=1e-9)
        assert b["compile"] == 0.0 and b["recovery"] == 0.0
        # the partition property: buckets sum EXACTLY to the window
        assert sum(b.values()) == pytest.approx(cp.wall_s, abs=1e-9)
        assert cp.wall_s == pytest.approx(1.5, abs=1e-9)

    def test_compile_overlap_claims_gap(self):
        evs = _synthetic_stream()
        # a 0.3s backend compile inside the 0.4s stall gap -> compile wins
        evs.insert(5, _ev(10, 101.3, "compile", "backend_compile", 0.3))
        cp = critpath.analyze(obs.merge_streams({"w0": evs}))
        assert cp.buckets["compile"] == pytest.approx(0.3, abs=1e-9)
        assert cp.buckets["stall"] == pytest.approx(0.1, abs=1e-9)
        assert sum(cp.buckets.values()) == pytest.approx(cp.wall_s, abs=1e-9)

    def test_recovery_task_buckets_whole(self):
        evs = _synthetic_stream()
        evs.append(_ev(7, 101.8, "task", "q1:exectape:a2c0", 0.2,
                       args={"q": "q1", "a": 2, "c": 0, "k": "exectape"}))
        cp = critpath.analyze(obs.merge_streams({"w0": evs}))
        assert cp.buckets["recovery"] == pytest.approx(0.2, abs=1e-9)
        assert cp.path[-1]["label"] == "q1:exectape:a2c0"

    def test_query_filter_and_render(self):
        evs = _synthetic_stream() + [
            _ev(20, 100.9, "task", "q2:input:a0c0", 0.1,
                args={"q": "q2", "a": 0, "c": 0, "k": "input"})]
        merged = obs.merge_streams({"w0": evs})
        cp = critpath.analyze(merged, query="q1")
        assert cp.n_tasks == 3  # the q2 neighbor is excluded
        text = cp.render()
        assert "critical path: query q1" in text
        assert "queue_wait" in text and "stall" in text
        js = cp.to_json()
        assert js["bucket_sum_s"] == pytest.approx(js["wall_s"], abs=1e-6)
        # majority-query selection without an explicit filter
        assert critpath.analyze(merged).query == "q1"

    def test_overlapping_cross_process_tasks_still_partition(self):
        """Cross-process chains can OVERLAP in time (the consumer pops a
        pushed batch before the producer's task event lands): the overlap
        must be attributed once, keeping bucket sum == window."""
        streams = {
            "w0": [_ev(0, 101.0, "task", "q1:input:a0c0", 1.0,
                       args={"q": "q1", "a": 0, "c": 0, "k": "input",
                             "outs": [0]})],
            "w1": [_ev(0, 101.3, "task", "q1:exec:a1c0", 0.4,
                       args={"q": "q1", "a": 1, "c": 0, "k": "exec",
                             "src": 0, "in": [[0, 0]]})],
        }
        cp = critpath.analyze(obs.merge_streams(streams))
        # consumer starts 100.9, producer ends 101.0: 0.1s overlap
        assert cp.wall_s == pytest.approx(1.3, abs=1e-9)
        assert sum(cp.buckets.values()) == pytest.approx(1.3, abs=1e-9)
        assert cp.buckets["other"] == pytest.approx(1.3, abs=1e-9)
        assert len(cp.path) == 2 and cp.path[1]["gap_s"] == 0.0

    def test_no_task_events_returns_none(self):
        merged = obs.merge_streams({"w0": [_ev(0, 1.0, "hb", "w")]})
        assert critpath.analyze(merged) is None

    def test_summarize_queries_orders_by_volume(self):
        evs = _synthetic_stream() + [
            _ev(20, 100.9, "task", "q2:input:a0c0", 0.1,
                args={"q": "q2", "a": 0, "c": 0, "k": "input"})]
        cps = critpath.summarize_queries(obs.merge_streams({"w0": evs}))
        assert [c.query for c in cps] == ["q1", "q2"]


class TestEndToEnd:
    def test_real_query_buckets_reconcile_with_wall(self):
        import time

        r = np.random.default_rng(0)
        t = pa.table({"k": r.integers(0, 16, 50_000).astype(np.int64),
                      "v": r.integers(0, 100, 50_000).astype(np.int64)})
        ctx = QuokkaContext()
        q = lambda: (ctx.from_arrow(t).groupby("k")  # noqa: E731
                     .agg_sql("sum(v) as sv").collect())
        q()  # warm the kernel set: compiles are not what this test times
        t0 = time.time()
        with critpath.profile() as p:
            df = q()
        wall = time.time() - t0
        assert len(df) > 0
        cp = p.result
        assert cp is not None, "recorder must be on by default"
        total = sum(cp.buckets.values())
        # ISSUE 5 acceptance: bucket sums within 10% of measured wall time
        assert abs(total - wall) <= 0.1 * wall, (total, wall, cp.buckets)
        assert cp.n_path > 0
        assert cp.buckets["compute"] + cp.buckets["scan_read"] > 0


class TestDroppedCounter:
    def test_ring_overwrite_counts_drops_per_type(self):
        rec = FlightRecorder(capacity=16, enabled=True, sample={})
        assert rec.dropped_total == 0 and rec.dropped == {}
        for i in range(24):
            rec.record("k", f"e{i}")
        for i in range(16):
            rec.record("other", f"o{i}")
        # 40 recorded - 16 retained = 24 evicted, attributed by KIND: the
        # first 16 "k" events fell to the later "k"s, then the 16 "other"s
        assert rec.dropped_total == 24
        assert rec.dropped == {"k": 24}
        out = io.StringIO()
        rec.dump_text(out)
        assert "dropped 24 event(s)" in out.getvalue()
        assert "k=24" in out.getvalue()
        rec.reset()
        assert rec.dropped_total == 0 and rec.dropped == {}

    def test_sampling_elides_listed_kinds_only(self, monkeypatch):
        rec = FlightRecorder(capacity=64, enabled=True, sample={"task": 4})
        kept = sum(1 for i in range(16)
                   if rec.record("task", f"t{i}") >= 0)
        assert kept == 4  # deterministic 1-in-4
        assert rec.sampled == {"task": 12}
        assert all(rec.record("stall", f"s{i}") >= 0 for i in range(8))
        assert rec.dropped_total == 0  # sampling is not ring eviction
        rec.reset()
        assert rec.sampled == {}

    def test_sample_env_parsing(self, monkeypatch):
        from quokka_tpu.obs import recorder as rmod
        monkeypatch.setenv("QK_TRACE_SAMPLE", "8")
        rates = rmod._sample_from_env()
        assert rates and all(v == 8 for v in rates.values())
        assert set(rates) == set(rmod._DEFAULT_SAMPLED_KINDS)
        monkeypatch.setenv("QK_TRACE_SAMPLE", "task=8,rpc=2,junk,x=0")
        assert rmod._sample_from_env() == {"task": 8, "rpc": 2}
        monkeypatch.setenv("QK_TRACE_SAMPLE", "1")
        assert rmod._sample_from_env() == {}
        monkeypatch.setenv("QK_TRACE_SAMPLE", "")
        assert rmod._sample_from_env() == {}

    def test_stall_report_warns_on_drops(self):
        merged = obs.merge_streams({"w0": _synthetic_stream()})
        report = obs.stall_report("test", merged, {}, {}, {},
                                  dropped={"w0": 7, "w1": 0})
        assert "WARNING" in report and "w0=7" in report
        assert "w1" not in report.split("WARNING")[1].splitlines()[0]
        clean = obs.stall_report("test", merged, {}, {}, {},
                                 dropped={"w0": 0})
        assert "WARNING: flight-recorder" not in clean

    def test_stall_report_renders_per_type_drop_dicts(self):
        merged = obs.merge_streams({"w0": _synthetic_stream()})
        report = obs.stall_report(
            "test", merged, {}, {}, {},
            dropped={"w0": {"task": 5, "rpc": 2}, "w1": {}})
        line = report.split("WARNING")[1].splitlines()[0]
        assert "w0=7(rpc:2,task:5)" in line
        assert "w1" not in line
