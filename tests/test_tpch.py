"""TPC-H correctness tests (the reference's oracle strategy, SURVEY.md section 4,
with pandas instead of DuckDB as ground truth).  Queries follow the shapes in
the reference's apps/tpc-h/tpch.py; data comes from the mini-dbgen in
tpch_data.py, written to Parquet and read through the full engine."""

import datetime

import numpy as np
import pandas as pd
import pytest

from quokka_tpu import QuokkaContext, col, date

import tpch_data


@pytest.fixture(scope="module")
def env(tmp_path_factory):
    root = tmp_path_factory.mktemp("tpch")
    tables = tpch_data.generate(sf=0.003, seed=7)
    paths = tpch_data.write_parquet_dir(tables, str(root))
    ctx = QuokkaContext(io_channels=2, exec_channels=2)
    dfs = {k: t.to_pandas() for k, t in tables.items()}
    return ctx, paths, dfs


def streams(env):
    ctx, paths, _ = env
    return {name: ctx.read_parquet(p) for name, p in paths.items()}


def sorted_eq(got, exp, by, rtol=1e-8):
    got = got.sort_values(by).reset_index(drop=True)[list(exp.columns)]
    exp = exp.sort_values(by).reset_index(drop=True)
    pd.testing.assert_frame_equal(got, exp, check_dtype=False, rtol=rtol)


def test_q1(env):
    ctx, paths, dfs = env
    li = streams(env)["lineitem"]
    got = (
        li.filter_sql("l_shipdate <= date '1998-12-01' - interval '90' day")
        .groupby(["l_returnflag", "l_linestatus"])
        .agg_sql(
            "sum(l_quantity) as sum_qty, "
            "sum(l_extendedprice) as sum_base_price, "
            "sum(l_extendedprice * (1 - l_discount)) as sum_disc_price, "
            "sum(l_extendedprice * (1 - l_discount) * (1 + l_tax)) as sum_charge, "
            "avg(l_quantity) as avg_qty, "
            "avg(l_extendedprice) as avg_price, "
            "avg(l_discount) as avg_disc, "
            "count(*) as count_order"
        )
        .collect()
    )
    l = dfs["lineitem"]
    f = l[l.l_shipdate <= datetime.date(1998, 9, 2)]
    exp = (
        f.groupby(["l_returnflag", "l_linestatus"])
        .apply(
            lambda d: pd.Series(
                {
                    "sum_qty": d.l_quantity.sum(),
                    "sum_base_price": d.l_extendedprice.sum(),
                    "sum_disc_price": (d.l_extendedprice * (1 - d.l_discount)).sum(),
                    "sum_charge": (
                        d.l_extendedprice * (1 - d.l_discount) * (1 + d.l_tax)
                    ).sum(),
                    "avg_qty": d.l_quantity.mean(),
                    "avg_price": d.l_extendedprice.mean(),
                    "avg_disc": d.l_discount.mean(),
                    "count_order": len(d),
                }
            ),
            include_groups=False,
        )
        .reset_index()
    )
    sorted_eq(got, exp, by=["l_returnflag", "l_linestatus"])


def test_q6(env):
    ctx, paths, dfs = env
    li = streams(env)["lineitem"]
    got = (
        li.filter_sql(
            "l_shipdate >= date '1994-01-01' and l_shipdate < date '1995-01-01' "
            "and l_discount between 0.05 and 0.07 and l_quantity < 24"
        )
        .agg_sql("sum(l_extendedprice * l_discount) as revenue")
        .collect()
    )
    l = dfs["lineitem"]
    f = l[
        (l.l_shipdate >= datetime.date(1994, 1, 1))
        & (l.l_shipdate < datetime.date(1995, 1, 1))
        & (l.l_discount >= 0.05)
        & (l.l_discount <= 0.07)
        & (l.l_quantity < 24)
    ]
    assert len(f) > 0
    np.testing.assert_allclose(
        got.revenue[0], (f.l_extendedprice * f.l_discount).sum(), rtol=1e-9
    )


def test_q3(env):
    ctx, paths, dfs = env
    s = streams(env)
    d = date("1995-03-15")
    got = (
        s["lineitem"]
        .filter(col("l_shipdate") > d)
        .join(
            s["orders"].filter(col("o_orderdate") < d),
            left_on="l_orderkey",
            right_on="o_orderkey",
        )
        .join(
            s["customer"].filter(col("c_mktsegment") == "BUILDING"),
            left_on="o_custkey",
            right_on="c_custkey",
        )
        .groupby(["l_orderkey", "o_orderdate", "o_shippriority"])
        .agg_sql("sum(l_extendedprice * (1 - l_discount)) as revenue")
        .top_k(["revenue"], 10, [True])
        .collect()
    )
    l, o, c = dfs["lineitem"], dfs["orders"], dfs["customer"]
    cut = datetime.date(1995, 3, 15)
    merged = (
        l[l.l_shipdate > cut]
        .merge(o[o.o_orderdate < cut], left_on="l_orderkey", right_on="o_orderkey")
        .merge(c[c.c_mktsegment == "BUILDING"], left_on="o_custkey", right_on="c_custkey")
    )
    merged["rev"] = merged.l_extendedprice * (1 - merged.l_discount)
    exp = (
        merged.groupby(["l_orderkey", "o_orderdate", "o_shippriority"])
        .rev.sum()
        .reset_index(name="revenue")
        .nlargest(10, "revenue")
    )
    assert len(exp) > 0
    got = got.sort_values("revenue", ascending=False).reset_index(drop=True)
    exp = exp.sort_values("revenue", ascending=False).reset_index(drop=True)
    np.testing.assert_allclose(got.revenue.to_numpy(), exp.revenue.to_numpy(), rtol=1e-9)


def test_q5(env):
    ctx, paths, dfs = env
    s = streams(env)
    got = (
        s["lineitem"]
        .join(
            s["orders"].filter_sql(
                "o_orderdate >= date '1994-01-01' and o_orderdate < date '1995-01-01'"
            ),
            left_on="l_orderkey",
            right_on="o_orderkey",
        )
        .join(s["customer"], left_on="o_custkey", right_on="c_custkey")
        .join(
            s["supplier"],
            left_on=["l_suppkey", "c_nationkey"],
            right_on=["s_suppkey", "s_nationkey"],
        )
        .join(s["nation"], left_on="c_nationkey", right_on="n_nationkey")
        .join(
            s["region"].filter(col("r_name") == "ASIA"),
            left_on="n_regionkey",
            right_on="r_regionkey",
        )
        .groupby("n_name")
        .agg_sql("sum(l_extendedprice * (1 - l_discount)) as revenue")
        .collect()
    )
    l, o, c = dfs["lineitem"], dfs["orders"], dfs["customer"]
    su, n, r = dfs["supplier"], dfs["nation"], dfs["region"]
    of = o[
        (o.o_orderdate >= datetime.date(1994, 1, 1))
        & (o.o_orderdate < datetime.date(1995, 1, 1))
    ]
    m = (
        l.merge(of, left_on="l_orderkey", right_on="o_orderkey")
        .merge(c, left_on="o_custkey", right_on="c_custkey")
        .merge(
            su,
            left_on=["l_suppkey", "c_nationkey"],
            right_on=["s_suppkey", "s_nationkey"],
        )
        .merge(n, left_on="c_nationkey", right_on="n_nationkey")
        .merge(r[r.r_name == "ASIA"], left_on="n_regionkey", right_on="r_regionkey")
    )
    m["rev"] = m.l_extendedprice * (1 - m.l_discount)
    exp = m.groupby("n_name").rev.sum().reset_index(name="revenue")
    assert len(exp) > 0
    sorted_eq(got, exp, by=["n_name"], rtol=1e-9)


def test_q12(env):
    ctx, paths, dfs = env
    s = streams(env)
    got = (
        s["lineitem"]
        .filter_sql(
            "l_shipmode in ('MAIL', 'SHIP') and l_commitdate < l_receiptdate "
            "and l_shipdate < l_commitdate and l_receiptdate >= date '1994-01-01' "
            "and l_receiptdate < date '1995-01-01'"
        )
        .join(s["orders"], left_on="l_orderkey", right_on="o_orderkey")
        .groupby("l_shipmode")
        .agg_sql(
            "sum(case when o_orderpriority = '1-URGENT' or o_orderpriority = '2-HIGH' "
            "then 1 else 0 end) as high_line_count, "
            "sum(case when o_orderpriority <> '1-URGENT' and o_orderpriority <> '2-HIGH' "
            "then 1 else 0 end) as low_line_count"
        )
        .collect()
    )
    l, o = dfs["lineitem"], dfs["orders"]
    f = l[
        l.l_shipmode.isin(["MAIL", "SHIP"])
        & (l.l_commitdate < l.l_receiptdate)
        & (l.l_shipdate < l.l_commitdate)
        & (l.l_receiptdate >= datetime.date(1994, 1, 1))
        & (l.l_receiptdate < datetime.date(1995, 1, 1))
    ]
    m = f.merge(o, left_on="l_orderkey", right_on="o_orderkey")
    hi = m.o_orderpriority.isin(["1-URGENT", "2-HIGH"])
    exp = (
        pd.DataFrame(
            {"l_shipmode": m.l_shipmode, "high": hi.astype(int), "low": (~hi).astype(int)}
        )
        .groupby("l_shipmode")
        .agg(high_line_count=("high", "sum"), low_line_count=("low", "sum"))
        .reset_index()
    )
    assert len(exp) > 0
    sorted_eq(got, exp, by=["l_shipmode"])


def test_q14(env):
    ctx, paths, dfs = env
    s = streams(env)
    got = (
        s["lineitem"]
        .filter_sql("l_shipdate >= date '1995-09-01' and l_shipdate < date '1995-10-01'")
        .join(s["part"], left_on="l_partkey", right_on="p_partkey")
        .agg_sql(
            "100.00 * sum(case when p_type like 'PROMO%' "
            "then l_extendedprice * (1 - l_discount) else 0 end) / "
            "sum(l_extendedprice * (1 - l_discount)) as promo_revenue"
        )
        .collect()
    )
    l, p = dfs["lineitem"], dfs["part"]
    f = l[
        (l.l_shipdate >= datetime.date(1995, 9, 1))
        & (l.l_shipdate < datetime.date(1995, 10, 1))
    ]
    m = f.merge(p, left_on="l_partkey", right_on="p_partkey")
    rev = m.l_extendedprice * (1 - m.l_discount)
    promo = rev.where(m.p_type.str.startswith("PROMO"), 0.0)
    exp = 100.0 * promo.sum() / rev.sum()
    np.testing.assert_allclose(got.promo_revenue[0], exp, rtol=1e-9)


def test_q4(env):
    """Semi-join shape (exists subquery in the reference)."""
    ctx, paths, dfs = env
    s = streams(env)
    got = (
        s["orders"]
        .filter_sql(
            "o_orderdate >= date '1993-07-01' and o_orderdate < date '1993-10-01'"
        )
        .join(
            s["lineitem"].filter_sql("l_commitdate < l_receiptdate"),
            left_on="o_orderkey",
            right_on="l_orderkey",
            how="semi",
        )
        .groupby("o_orderpriority")
        .agg_sql("count(*) as order_count")
        .collect()
    )
    o, l = dfs["orders"], dfs["lineitem"]
    import datetime

    of = o[
        (o.o_orderdate >= datetime.date(1993, 7, 1))
        & (o.o_orderdate < datetime.date(1993, 10, 1))
    ]
    lk = set(l[l.l_commitdate < l.l_receiptdate].l_orderkey)
    sel = of[of.o_orderkey.isin(lk)]
    exp = sel.groupby("o_orderpriority").size().reset_index(name="order_count")
    assert len(exp) > 0
    sorted_eq(got, exp, by=["o_orderpriority"])


def test_q10(env):
    """Join chain + group-by + top-k by revenue."""
    ctx, paths, dfs = env
    s = streams(env)
    got = (
        s["lineitem"]
        .filter_sql("l_returnflag = 'R'")
        .join(
            s["orders"].filter_sql(
                "o_orderdate >= date '1993-10-01' and o_orderdate < date '1994-01-01'"
            ),
            left_on="l_orderkey",
            right_on="o_orderkey",
        )
        .join(s["customer"], left_on="o_custkey", right_on="c_custkey")
        .join(s["nation"], left_on="c_nationkey", right_on="n_nationkey")
        # the build side's join key (c_custkey) is consumed by the join;
        # group on the equal probe-side key o_custkey
        .groupby(["o_custkey", "c_name", "n_name"])
        .agg_sql("sum(l_extendedprice * (1 - l_discount)) as revenue")
        .top_k(["revenue"], 20, [True])
        .collect()
    )
    import datetime

    l, o, c, n = dfs["lineitem"], dfs["orders"], dfs["customer"], dfs["nation"]
    of = o[
        (o.o_orderdate >= datetime.date(1993, 10, 1))
        & (o.o_orderdate < datetime.date(1994, 1, 1))
    ]
    m = (
        l[l.l_returnflag == "R"]
        .merge(of, left_on="l_orderkey", right_on="o_orderkey")
        .merge(c, left_on="o_custkey", right_on="c_custkey")
        .merge(n, left_on="c_nationkey", right_on="n_nationkey")
    )
    m["rev"] = m.l_extendedprice * (1 - m.l_discount)
    exp = (
        m.groupby(["c_custkey", "c_name", "n_name"])
        .rev.sum()
        .reset_index(name="revenue")
        .nlargest(20, "revenue")
    )
    assert len(exp) > 0
    np.testing.assert_allclose(
        np.sort(got.revenue.to_numpy())[::-1],
        np.sort(exp.revenue.to_numpy())[::-1],
        rtol=1e-9,
    )


def test_q19(env):
    """Disjunctive multi-attribute predicate (OR of ANDs)."""
    ctx, paths, dfs = env
    s = streams(env)
    got = (
        s["lineitem"]
        .join(s["part"], left_on="l_partkey", right_on="p_partkey")
        .filter_sql(
            "(p_brand = 'Brand#12' and l_quantity >= 1 and l_quantity <= 11 "
            " and p_size between 1 and 5) "
            "or (p_brand = 'Brand#23' and l_quantity >= 10 and l_quantity <= 20 "
            " and p_size between 1 and 10) "
            "or (p_brand = 'Brand#34' and l_quantity >= 20 and l_quantity <= 30 "
            " and p_size between 1 and 15)"
        )
        .agg_sql("sum(l_extendedprice * (1 - l_discount)) as revenue, count(*) as n")
        .collect()
    )
    l, p = dfs["lineitem"], dfs["part"]
    m = l.merge(p, left_on="l_partkey", right_on="p_partkey")
    cond = (
        ((m.p_brand == "Brand#12") & m.l_quantity.between(1, 11) & m.p_size.between(1, 5))
        | ((m.p_brand == "Brand#23") & m.l_quantity.between(10, 20) & m.p_size.between(1, 10))
        | ((m.p_brand == "Brand#34") & m.l_quantity.between(20, 30) & m.p_size.between(1, 15))
    )
    f = m[cond]
    assert len(f) > 0
    np.testing.assert_allclose(
        got.revenue[0], (f.l_extendedprice * (1 - f.l_discount)).sum(), rtol=1e-9
    )
    assert got.n[0] == len(f)


def test_q13(env):
    """Left join + count + distribution of counts (agg over agg)."""
    ctx, paths, dfs = env
    s = streams(env)
    got = (
        s["customer"]
        .join(
            s["orders"].filter(~col("o_comment").str.contains("special")),
            left_on="c_custkey",
            right_on="o_custkey",
            how="left",
        )
        .groupby("c_custkey")
        .agg_sql("count(o_orderkey) as c_count")
        .groupby("c_count")
        .agg_sql("count(*) as custdist")
        .collect()
    )
    c, o = dfs["customer"], dfs["orders"]
    of = o[~o.o_comment.str.contains("special")]
    m = c.merge(of, left_on="c_custkey", right_on="o_custkey", how="left")
    cc = m.groupby("c_custkey").o_orderkey.count().reset_index(name="c_count")
    exp = cc.groupby("c_count").size().reset_index(name="custdist")
    assert len(exp) > 1
    sorted_eq(got, exp, by=["c_count"])
