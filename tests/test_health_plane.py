"""Health & progress plane (ISSUE 17): the progress estimator's known
answers (cold size_hint fallback, warm cardprofile blend), the monotone
clamp under out-of-order ledger views, ETA math on a synthetic timeline,
GC idempotence; the alert rules' known-answer matrix with edge-triggered
counting and the ok/degraded/critical fold; the history ring's rate math
and depth eviction; and bench --trend's monotone-decline gate."""

import importlib.util
import json
import os

import pytest

from quokka_tpu import obs
from quokka_tpu.obs import alerts, opstats
from quokka_tpu.obs.alerts import AlertEngine
from quokka_tpu.obs.history import HistoryRing
from quokka_tpu.obs.progress import ProgressTracker, _estimate

# ---------------------------------------------------------------------------
# progress: the pure estimator
# ---------------------------------------------------------------------------


def _view(scanned=0, hint=0, ops=None, qid="q1", fp="fp1", t0=0.0):
    return {"query_id": qid, "plan_fp": fp, "t0": t0,
            "size_hint_bytes": hint, "scanned_bytes": scanned,
            "scanned_rows": 0, "op_rows_out": ops or {}}


class TestEstimate:
    def test_cold_plan_falls_back_to_size_hint(self):
        raw, basis, detail = _estimate(_view(scanned=50, hint=100), None)
        assert (raw, basis) == (0.5, "size_hint")
        assert detail["source_bytes_total"] == 100
        assert detail["source_bytes_done"] == 50

    def test_no_denominator_reports_none_basis(self):
        raw, basis, _ = _estimate(_view(scanned=50, hint=0), None)
        assert (raw, basis) == (0.0, "none")

    def test_warm_plan_blends_scan_and_operator_completion(self):
        profile = {"source_bytes": 200, "rows": {"a2:agg": 10, "a3:x": 0}}
        raw, basis, detail = _estimate(
            _view(scanned=100, ops={"a2:agg": 5, "a3:x": 7}), profile)
        # scan 100/200 = 0.5; op a2 5/10 = 0.5 (a3 has no prior: skipped);
        # blend = 0.5*0.5 + 0.5*0.5
        assert basis == "cardprofile"
        assert raw == pytest.approx(0.5)
        assert detail["profiled_ops"] == 1
        assert detail["op_completion"] == pytest.approx(0.5)
        assert detail["source_bytes_total"] == 200

    def test_warm_plan_without_op_priors_uses_scan_fraction(self):
        raw, basis, detail = _estimate(
            _view(scanned=150), {"source_bytes": 200, "rows": {}})
        assert basis == "cardprofile"
        assert raw == pytest.approx(0.75)
        assert detail["profiled_ops"] == 0

    def test_overshoot_clamps_to_one(self):
        profile = {"source_bytes": 100, "rows": {"a2:agg": 4}}
        raw, _, _ = _estimate(
            _view(scanned=300, ops={"a2:agg": 9}), profile)
        assert raw == pytest.approx(1.0)


# ---------------------------------------------------------------------------
# progress: the tracker (monotone clamp, ETA, GC)
# ---------------------------------------------------------------------------


@pytest.fixture
def ledger(monkeypatch):
    """A synthetic opstats ledger: tests mutate views[qid] to feed the
    tracker; plan profiles resolve to None (cold) unless overridden."""
    views = {}
    monkeypatch.setattr(opstats.OPSTATS, "progress_view",
                        lambda qid: views.get(qid))
    monkeypatch.setattr(opstats, "_plan_entry", lambda fp: None)
    return views


class TestTracker:
    def test_unknown_query_returns_none(self, ledger):
        assert ProgressTracker().snapshot("nope") is None

    def test_fraction_monotone_under_out_of_order_views(self, ledger):
        tr = ProgressTracker()
        ledger["qm"] = _view(scanned=80, hint=100, qid="qm")
        assert tr.snapshot("qm", now=1.0)["fraction"] == pytest.approx(0.8)
        # an out-of-order (shrinking) ledger report never moves the bar back
        ledger["qm"] = _view(scanned=40, hint=100, qid="qm")
        assert tr.snapshot("qm", now=2.0)["fraction"] == pytest.approx(0.8)
        # and a live query never claims completion: capped below 1.0
        ledger["qm"] = _view(scanned=100, hint=100, qid="qm")
        snap = tr.snapshot("qm", now=3.0)
        assert snap["fraction"] == pytest.approx(0.99)
        assert snap["basis"] == "size_hint"
        tr.on_query_gc("qm")

    def test_eta_known_answer_on_synthetic_timeline(self, ledger):
        tr = ProgressTracker()
        ledger["qe"] = _view(scanned=20, hint=100, qid="qe")
        first = tr.snapshot("qe", now=100.0)
        assert first["eta_s"] is None  # one sample: no rate yet
        ledger["qe"] = _view(scanned=40, hint=100, qid="qe")
        snap = tr.snapshot("qe", now=110.0)
        # rate = (0.4 - 0.2) / 10s = 0.02/s; eta = (1 - 0.4) / 0.02 = 30s
        assert snap["rate_per_s"] == pytest.approx(0.02)
        assert snap["eta_s"] == pytest.approx(30.0)
        tr.on_query_gc("qe")

    def test_gauges_exported_live_and_removed_on_gc(self, ledger):
        tr = ProgressTracker()
        ledger["qg"] = _view(scanned=50, hint=100, qid="qg")
        tr.snapshot("qg", now=1.0)
        snap = obs.REGISTRY.snapshot()
        assert snap["progress.fraction.qg"] == pytest.approx(0.5)
        assert snap["progress.eta_s.qg"] == -1.0  # no rate yet -> no ETA
        tr.on_query_gc("qg")
        snap = obs.REGISTRY.snapshot()
        assert "progress.fraction.qg" not in snap
        assert "progress.eta_s.qg" not in snap

    def test_gc_stamps_finished_and_is_idempotent(self, ledger):
        tr = ProgressTracker()
        ledger["qd"] = _view(scanned=50, hint=100, qid="qd")
        tr.snapshot("qd", now=1.0)
        final = tr.on_query_gc("qd", finished=True)
        assert final["fraction"] == 1.0 and final["eta_s"] == 0.0
        assert tr.last_finished()["query_id"] == "qd"

    def test_failed_query_keeps_honest_fraction_across_double_gc(
            self, ledger):
        tr = ProgressTracker()
        ledger["qf"] = _view(scanned=40, hint=100, qid="qf")
        tr.snapshot("qf", now=1.0)
        # session.finish() GCs with finished=False on error ...
        snap = tr.on_query_gc("qf", finished=False)
        assert snap["fraction"] == pytest.approx(0.4)
        del ledger["qf"]
        # ... then the engine's cleanup hook fires again with the default
        # finished=True: the stash must NOT be restamped to 1.0
        again = tr.on_query_gc("qf")
        assert again["fraction"] == pytest.approx(0.4)
        assert tr.last_finished()["fraction"] == pytest.approx(0.4)


# ---------------------------------------------------------------------------
# alerts: the rule matrix
# ---------------------------------------------------------------------------


def _sample(counters=None, gauges=None, hists=None, t=0.0):
    return {"t": t, "counters": counters or {}, "gauges": gauges or {},
            "histograms": hists or {}}


class TestAlertRules:
    def test_channel_skew_fires_on_per_edge_gauge_only(self):
        hot = _sample(gauges={"shuffle.skew.q1.a0-a1": 3.0})
        assert "a0-a1" in alerts._rule_channel_skew(hot, None, {})
        cool = _sample(gauges={"shuffle.skew.q1.a0-a1": 1.5})
        assert alerts._rule_channel_skew(cool, None, {}) is None
        # the process-lifetime max gauge never resets: it must not pin the
        # alert after the skewed query is long gone
        global_max = _sample(gauges={"shuffle.skew": 99.0})
        assert alerts._rule_channel_skew(global_max, None, {}) is None

    def test_watermark_lag_threshold(self, monkeypatch):
        monkeypatch.setenv("QK_ALERT_WM_LAG_S", "30")
        hot = _sample(gauges={"stream.watermark_lag_s.s1": 45.0})
        assert "45.0s" in alerts._rule_watermark_lag(hot, None, {})
        cool = _sample(gauges={"stream.watermark_lag_s.s1": 5.0})
        assert alerts._rule_watermark_lag(cool, None, {}) is None

    def test_mem_budget_critical_threshold(self, monkeypatch):
        monkeypatch.setenv("QK_SERVICE_MEM_BUDGET", "1000")
        hot = _sample(gauges={"mem.live_bytes.q1": 950.0})
        assert "95%" in alerts._rule_mem_budget(hot, None, {})
        cool = _sample(gauges={"mem.live_bytes.q1": 500.0})
        assert alerts._rule_mem_budget(cool, None, {}) is None

    def test_queue_wait_needs_high_p95_and_fresh_arrivals(self, monkeypatch):
        monkeypatch.setenv("QK_ALERT_QUEUE_P95_S", "0.5")
        obs.REGISTRY.remove("admission.queue_wait_s")
        try:
            h = obs.REGISTRY.histogram("admission.queue_wait_s")
            for _ in range(10):
                h.observe(2.0)
            cur = _sample(hists={"admission.queue_wait_s": (10, 20.0)})
            prev = _sample(hists={"admission.queue_wait_s": (4, 8.0)})
            assert "p95" in alerts._rule_queue_wait(cur, prev, {})
            # same count since last sample: the pileup is historical — the
            # cumulative histogram must not pin the alert forever
            assert alerts._rule_queue_wait(cur, cur, {}) is None
        finally:
            obs.REGISTRY.remove("admission.queue_wait_s")

    def test_no_progress_streak_then_recovery(self, monkeypatch):
        monkeypatch.setenv("QK_ALERT_STALL_EVALS", "3")
        state = {}
        stuck = _sample(gauges={"progress.fraction.q1": 0.42})
        assert alerts._rule_no_progress(stuck, stuck, state) is None
        assert alerts._rule_no_progress(stuck, stuck, state) is None
        msg = alerts._rule_no_progress(stuck, stuck, state)
        assert msg is not None and "q1" in msg and "42%" in msg
        # progress resumes: streak resets, three more evals to re-fire
        moved = _sample(gauges={"progress.fraction.q1": 0.43})
        assert alerts._rule_no_progress(moved, stuck, state) is None
        assert state["streaks"] == {}

    def test_no_progress_ignores_nearly_done_queries(self):
        state = {}
        tail = _sample(gauges={"progress.fraction.q1": 0.99})
        for _ in range(5):
            assert alerts._rule_no_progress(tail, tail, state) is None

    def test_mem_leak_and_integrity_fire_on_counter_deltas(self):
        cur = _sample(counters={"mem.leaked": 3, "integrity.corrupt": 2})
        prev = _sample(counters={"mem.leaked": 1, "integrity.corrupt": 2})
        assert "2 allocation(s)" in alerts._rule_mem_leak(cur, prev, {})
        assert alerts._rule_integrity(cur, prev, {}) is None
        prev2 = _sample(counters={"mem.leaked": 3, "integrity.corrupt": 0})
        assert alerts._rule_mem_leak(cur, prev2, {}) is None
        assert "2 checksum" in alerts._rule_integrity(cur, prev2, {})


class TestAlertEngine:
    def test_edge_triggered_fire_refresh_clear(self):
        eng = AlertEngine()
        hot = {"shuffle.skew.q9.a0-a1": 9.0}
        fired0 = obs.REGISTRY.snapshot().get("alert.channel_skew", 0)
        fired = eng.evaluate(_sample(gauges=hot, t=1.0))
        assert [f["rule"] for f in fired] == ["channel_skew"]
        assert eng.health()["status"] == "degraded"
        since = eng.health()["firing"][0]["since"]
        # staying hot: no new fire, no counter bump, edge time kept
        assert eng.evaluate(_sample(gauges=hot, t=2.0)) == []
        assert eng.health()["firing"][0]["since"] == since
        assert obs.REGISTRY.snapshot().get(
            "alert.channel_skew", 0) - fired0 == 1
        # clearing recovers
        eng.evaluate(_sample(t=3.0))
        assert eng.health() == {"status": "ok", "firing": [],
                                "evaluated_at": 3.0}

    def test_critical_rule_wins_the_verdict(self, monkeypatch):
        monkeypatch.setenv("QK_SERVICE_MEM_BUDGET", "1000")
        eng = AlertEngine()
        eng.evaluate(_sample(gauges={"mem.live_bytes.q1": 990.0,
                                     "shuffle.skew.q1.a0-a1": 5.0}, t=1.0))
        h = eng.health()
        assert h["status"] == "critical"
        assert [f["rule"] for f in h["firing"]] == ["channel_skew",
                                                    "mem_budget"]
        assert obs.REGISTRY.snapshot().get("health.status") == 2.0
        eng.evaluate(_sample(t=2.0))
        assert obs.REGISTRY.snapshot().get("health.status") == 0.0

    def test_broken_rule_does_not_sink_the_evaluation(self, monkeypatch):
        eng = AlertEngine()
        monkeypatch.setattr(alerts, "RULES", alerts.RULES + (
            ("boom", "warn",
             lambda cur, prev, st: (_ for _ in ()).throw(RuntimeError())),
        ))
        assert eng.evaluate(_sample(t=1.0)) == []
        assert eng.health()["status"] == "ok"


# ---------------------------------------------------------------------------
# history: the sample ring
# ---------------------------------------------------------------------------


class TestHistoryRing:
    def test_depth_eviction_keeps_newest(self, monkeypatch):
        monkeypatch.setenv("QK_HISTORY_DEPTH", "3")
        ring = HistoryRing()
        for i in range(5):
            ring.record(now=float(i))
        kept = ring.samples()
        assert [s["t"] for s in kept] == [2.0, 3.0, 4.0]
        assert ring.payload()["depth"] == 3

    def test_rates_derive_only_for_moved_counters(self):
        ring = HistoryRing()
        moving = obs.REGISTRY.counter("healthtest.moving")
        obs.REGISTRY.counter("healthtest.static").inc()
        try:
            ring.record(now=100.0)
            moving.inc(5)
            obs.REGISTRY.histogram("healthtest.h_s").observe(0.5)
            ring.record(now=110.0)
            rates = ring.rates()
            assert rates["healthtest.moving"] == [
                {"t": 110.0, "rate": 0.5}]
            assert "healthtest.static" not in rates
            # histogram observation rates under the .count key
            assert rates["healthtest.h_s.count"] == [
                {"t": 110.0, "rate": 0.1}]
        finally:
            obs.REGISTRY.remove("healthtest.moving", "healthtest.static",
                                "healthtest.h_s")

    def test_record_counts_itself(self):
        ring = HistoryRing()
        before = obs.REGISTRY.snapshot().get("history.samples", 0)
        sample = ring.record(now=1.0)
        assert sample["t"] == 1.0
        assert {"counters", "gauges", "histograms"} <= set(sample)
        assert obs.REGISTRY.snapshot()["history.samples"] == before + 1


# ---------------------------------------------------------------------------
# bench --trend: the cross-round decline gate
# ---------------------------------------------------------------------------

_BENCH = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "bench.py")


@pytest.fixture(scope="module")
def bench():
    spec = importlib.util.spec_from_file_location("qk_bench", _BENCH)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _write_round(dirpath, n, values):
    lines = [{"metric": m, "value": v, "unit": "x", "vs_baseline": v,
              "detail": {}} for m, v in values.items()]
    path = os.path.join(str(dirpath), f"BENCH_r{n:02d}.json")
    with open(path, "w", encoding="utf-8") as f:
        json.dump(lines, f)


class TestBenchTrend:
    def test_monotone_decline_over_window_exits_nonzero(self, bench,
                                                        tmp_path, capsys):
        for i, v in enumerate((1.0, 0.9, 0.8), start=1):
            _write_round(tmp_path, i, {"m_leak": v, "m_fine": 1.0})
        rc = bench.trend_main(["--dir", str(tmp_path)])
        out = capsys.readouterr().out
        assert rc == 1
        assert "TREND REGRESSION" in out and "m_leak" in out
        assert "DECLINING" in out

    def test_clean_trajectory_exits_zero(self, bench, tmp_path, capsys):
        for i, v in enumerate((0.8, 0.9, 0.85), start=1):
            _write_round(tmp_path, i, {"m": v})
        rc = bench.trend_main(["--dir", str(tmp_path)])
        assert rc == 0
        assert "clean" in capsys.readouterr().out

    def test_decline_across_recording_gap_is_not_attributed(self, bench,
                                                            tmp_path,
                                                            capsys):
        # m declines 1.0 -> 0.9 -> 0.8 but round 2 never recorded it: the
        # gap spans a potential box re-baseline, so the gate must not trip
        _write_round(tmp_path, 1, {"m": 1.0, "anchor": 1.0})
        _write_round(tmp_path, 2, {"anchor": 1.0})
        _write_round(tmp_path, 3, {"m": 0.9, "anchor": 1.0})
        _write_round(tmp_path, 4, {"m": 0.8, "anchor": 1.0})
        rc = bench.trend_main(["--dir", str(tmp_path)])
        assert rc == 0, capsys.readouterr().out

    def test_too_few_artifacts_is_a_usage_error(self, bench, tmp_path):
        _write_round(tmp_path, 1, {"m": 1.0})
        assert bench.trend_main(["--dir", str(tmp_path)]) == 2
