"""Expression AST, SQL parser, and expression->JAX compiler tests."""

import datetime

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from quokka_tpu import sqlparse
from quokka_tpu.expression import col, date, interval, lit, split_conjuncts, when
from quokka_tpu.ops import bridge, expr_compile, kernels


def eval_mask(expr, table):
    b = bridge.arrow_to_device(table)
    m = expr_compile.evaluate_predicate(expr, b)
    return np.asarray(m)[np.asarray(b.valid)]


def eval_col(expr, table):
    b = bridge.arrow_to_device(table)
    c = expr_compile.evaluate_to_column(expr, b)
    out = bridge.device_to_arrow(
        type(b)({"x": c}, b.valid, b.nrows)
    )
    return out.column("x").to_numpy(zero_copy_only=False)


class TestPythonExprs:
    def test_arith_and_compare(self, table, pdf):
        e = (col("v") * 2 + col("q")) > 30
        got = eval_mask(e, table)
        np.testing.assert_array_equal(got, (pdf.v * 2 + pdf.q) > 30)

    def test_and_or_not(self, table, pdf):
        e = ((col("k") < 5) | (col("k") > 15)) & ~(col("q") == 10)
        got = eval_mask(e, table)
        exp = ((pdf.k < 5) | (pdf.k > 15)) & ~(pdf.q == 10)
        np.testing.assert_array_equal(got, exp)

    def test_string_equality(self, table, pdf):
        got = eval_mask(col("s") == "banana", table)
        np.testing.assert_array_equal(got, pdf.s == "banana")

    def test_string_contains_like(self, table, pdf):
        got = eval_mask(col("s").str.contains("an"), table)
        np.testing.assert_array_equal(got, pdf.s.str.contains("an"))
        got = eval_mask(col("s").str.like("%rry"), table)
        np.testing.assert_array_equal(got, pdf.s.str.endswith("rry"))

    def test_is_in(self, table, pdf):
        got = eval_mask(col("s").is_in(["apple", "date"]), table)
        np.testing.assert_array_equal(got, pdf.s.isin(["apple", "date"]))
        got = eval_mask(col("k").is_in([1, 2, 3]), table)
        np.testing.assert_array_equal(got, pdf.k.isin([1, 2, 3]))

    def test_date_compare(self, table, pdf):
        cutoff = datetime.date(1997, 6, 1)
        got = eval_mask(col("d") <= date("1997-06-01"), table)
        np.testing.assert_array_equal(got, pdf.d <= cutoff)

    def test_date_interval_arith(self, table, pdf):
        e = col("d") <= (date("1998-12-01") - interval(90, "day"))
        got = eval_mask(e, table)
        cutoff = datetime.date(1998, 12, 1) - datetime.timedelta(days=90)
        np.testing.assert_array_equal(got, pdf.d <= cutoff)

    def test_dt_year_month(self, table, pdf):
        got = eval_col(col("d").dt.year, table)
        np.testing.assert_array_equal(got, pd.DatetimeIndex(pdf.d).year)
        got = eval_col(col("d").dt.month, table)
        np.testing.assert_array_equal(got, pd.DatetimeIndex(pdf.d).month)

    def test_case_when(self, table, pdf):
        e = when(col("k") < 10).then(1.0).otherwise(0.0)
        got = eval_col(e, table)
        np.testing.assert_array_equal(got, np.where(pdf.k < 10, 1.0, 0.0))

    def test_string_transform_slice(self, table, pdf):
        e = col("s").str.slice(0, 2) == "ba"
        got = eval_mask(e, table)
        np.testing.assert_array_equal(got, pdf.s.str[:2] == "ba")

    def test_required_columns(self):
        e = (col("a") + col("b")) > col("c")
        assert e.required_columns() == {"a", "b", "c"}

    def test_split_conjuncts(self):
        e = (col("a") > 1) & (col("b") > 2) & (col("c") > 3)
        parts = split_conjuncts(e)
        assert len(parts) == 3


class TestSqlParser:
    def test_simple_filter(self, table, pdf):
        e = sqlparse.parse_expression("q > 25 and s = 'apple'")
        got = eval_mask(e, table)
        np.testing.assert_array_equal(got, (pdf.q > 25) & (pdf.s == "apple"))

    def test_tpch_q1_filter(self, table, pdf):
        e = sqlparse.parse_expression("d <= date '1998-12-01' - interval '90' day")
        got = eval_mask(e, table)
        cutoff = datetime.date(1998, 12, 1) - datetime.timedelta(days=90)
        np.testing.assert_array_equal(got, pdf.d <= cutoff)

    def test_between_in_like(self, table, pdf):
        e = sqlparse.parse_expression(
            "k between 5 and 10 and s in ('apple','cherry') and s like '%e%'"
        )
        got = eval_mask(e, table)
        exp = (
            pdf.k.between(5, 10)
            & pdf.s.isin(["apple", "cherry"])
            & pdf.s.str.contains("e")
        )
        np.testing.assert_array_equal(got, exp)

    def test_arith_precedence(self, table, pdf):
        e = sqlparse.parse_expression("v * 2 + q / 2 > 20")
        got = eval_mask(e, table)
        np.testing.assert_array_equal(got, pdf.v * 2 + pdf.q / 2 > 20)

    def test_case_expression(self, table, pdf):
        e = sqlparse.parse_expression("case when k < 10 then 1 else 0 end")
        got = eval_col(e, table)
        np.testing.assert_array_equal(got, np.where(pdf.k < 10, 1, 0))

    def test_not_like(self, table, pdf):
        e = sqlparse.parse_expression("s not like '%an%'")
        got = eval_mask(e, table)
        np.testing.assert_array_equal(got, ~pdf.s.str.contains("an"))

    def test_extract(self, table, pdf):
        e = sqlparse.parse_expression("extract(year from d)")
        got = eval_col(e, table)
        np.testing.assert_array_equal(got, pd.DatetimeIndex(pdf.d).year)

    def test_select_list_with_aliases(self):
        exprs = sqlparse.parse_select_list("sum(a) as s, count(*) as n, avg(b * c) as m")
        assert [e.name for e in exprs] == ["s", "n", "m"]

    def test_substring(self, table, pdf):
        e = sqlparse.parse_expression("substring(s, 1, 3) = 'app'")
        got = eval_mask(e, table)
        np.testing.assert_array_equal(got, pdf.s.str[:3] == "app")

    def test_cast(self, table, pdf):
        e = sqlparse.parse_expression("cast(q as double) / 2")
        got = eval_col(e, table)
        np.testing.assert_allclose(got, pdf.q / 2)


class TestAggPlan:
    def test_q1_style_aggs(self, table, pdf):
        exprs = sqlparse.parse_select_list(
            "sum(q) as sum_qty, avg(v) as avg_v, count(*) as n, "
            "sum(q * (1 - v)) as disc, max(q) as mk"
        )
        plan = expr_compile.plan_aggregation(exprs)
        b = bridge.arrow_to_device(make_batch_table(table))
        # compute pre columns
        for name, e in plan.pre:
            b = b.with_column(name, expr_compile.evaluate_to_column(e, b))
        aggs = [
            (pname, op, None if tmp is None else b.columns[tmp].data)
            for (pname, op, tmp) in plan.partials
        ]
        g = kernels.compact(kernels.groupby_aggregate(b, ["k"], aggs))
        for name, e in plan.finals:
            g = g.with_column(name, expr_compile.evaluate_to_column(e, g))
        got = (
            bridge.device_to_arrow(g.select(["k"] + [n for n, _ in plan.finals]))
            .to_pandas()
            .sort_values("k")
            .reset_index(drop=True)
        )
        exp = (
            pdf.groupby("k")
            .apply(
                lambda df: pd.Series(
                    {
                        "sum_qty": df.q.sum(),
                        "avg_v": df.v.mean(),
                        "n": len(df),
                        "disc": (df.q * (1 - df.v)).sum(),
                        "mk": df.q.max(),
                    }
                ),
                include_groups=False,
            )
            .reset_index()
        )
        pd.testing.assert_frame_equal(got, exp, check_dtype=False, rtol=1e-9)


def make_batch_table(table):
    return table


class TestStringCaseAndCast:
    def test_string_valued_case(self):
        import pyarrow as pa

        from quokka_tpu import QuokkaContext

        t = pa.table({"x": [1, 5, 9, None], "s": ["lo", "hi", "hi", None]})
        out = QuokkaContext().from_arrow(t).with_columns_sql(
            "case when x < 3 then 'small' when x < 7 then s else 'big' end as bucket"
        ).collect()
        # null x: both predicates false (3VL) -> ELSE branch
        assert out["bucket"].tolist() == ["small", "hi", "big", "big"]

    def test_string_case_null_else(self):
        import pyarrow as pa

        from quokka_tpu import QuokkaContext

        t = pa.table({"x": [1, 9]})
        out = QuokkaContext().from_arrow(t).with_columns_sql(
            "case when x < 3 then 'small' end as bucket"
        ).collect()
        assert out["bucket"].tolist()[0] == "small"
        assert out["bucket"].isna().tolist() == [False, True]

    def test_cast_to_string(self):
        import pyarrow as pa

        from quokka_tpu import QuokkaContext

        t = pa.table({
            "x": [1, 5, None],
            "f": [1.5, 2.25, 3.0],
            "d": pa.array([10957, None, 11100], type=pa.int32()).cast(pa.date32()),
        })
        out = QuokkaContext().from_arrow(t).with_columns_sql(
            "cast(x as varchar) as xs, cast(f as varchar) as fs, "
            "cast(d as varchar) as ds"
        ).collect()
        assert out["xs"].tolist()[:2] == ["1", "5"] and out["xs"].isna().iloc[2]
        assert out["fs"].tolist() == ["1.5", "2.25", "3.0"]
        assert out["ds"].iloc[0] == "2000-01-01" and out["ds"].isna().iloc[1]

    def test_string_case_groupby(self):
        import numpy as np
        import pyarrow as pa

        from quokka_tpu import QuokkaContext

        r = np.random.default_rng(0)
        t = pa.table({"x": r.integers(0, 100, 5000), "v": r.uniform(0, 1, 5000)})
        got = (
            QuokkaContext().from_arrow(t)
            .with_columns_sql(
                "case when x < 30 then 'low' when x < 70 then 'mid' "
                "else 'high' end as band"
            )
            .groupby("band").agg_sql("count(*) as n, sum(v) as sv")
            .collect().sort_values("band").reset_index(drop=True)
        )
        df = t.to_pandas()
        df["band"] = np.where(df.x < 30, "low", np.where(df.x < 70, "mid", "high"))
        exp = df.groupby("band").v.agg(["size", "sum"]).reset_index()
        assert got.band.tolist() == exp.band.tolist()
        assert got.n.tolist() == exp["size"].tolist()
        np.testing.assert_allclose(got.sv.to_numpy(), exp["sum"].to_numpy(), rtol=1e-9)
