"""Durable batch queries (ISSUE 19): crash-consistent resume manifests,
supervisor re-admission, and first-class cancellation/deadlines.

Acceptance pins: manifest roundtrip known-answers + loud ``ManifestMismatch``
on tamper/drift; orphan re-admission goes through NORMAL admission (FIFO, no
barging) and a duplicate resume of a LIVE query is refused; ``attach()``
drains exactly the undelivered tail past a client cursor; cancel and deadline
leave ZERO residue (namespace rows, spill/checkpoint/manifest files,
admission bytes); the resume fingerprint is restart-stable; the startup
janitor quarantines unreadable/foreign manifests instead of wedging.  The
actual SIGKILL-the-process path is exercised by
``quokka_tpu/service/resume_smoke.py`` (``make resume-smoke``) and the chaos
soak's ``batch-resume`` mode — these tests pin the in-process contracts.
"""

import os
import pickle
import shutil
import time

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from quokka_tpu import QuokkaContext, obs
from quokka_tpu.dataset.readers import InputArrowDataset
from quokka_tpu.runtime import integrity, scancache
from quokka_tpu.runtime import resume as bresume
from quokka_tpu.runtime.engine import TaskGraph
from quokka_tpu.runtime.tables import ControlStore
from quokka_tpu.service import (
    DeadlineExceeded,
    QueryCancelled,
    QueryService,
)


@pytest.fixture(autouse=True)
def fresh_scan_cache():
    scancache.clear()
    yield
    scancache.clear()


FT_CFG = {"fault_tolerance": True, "checkpoint_interval": 2}


def _small_table(n=8192, seed=0):
    r = np.random.default_rng(seed)
    # integer-valued floats: sums are order-exact, so a resumed/re-run query
    # must match the serial answer byte-for-byte
    return pa.table({"k": r.integers(0, 16, n).astype(np.int64),
                     "v": r.integers(0, 1000, n).astype(np.float64)})


class _SlowDS(InputArrowDataset):
    """Arrow reader with a per-lineage delay — a deterministic long-running
    query that stays in flight long enough to cancel/expire/queue behind."""

    def __init__(self, table, batch_rows=512, delay_s=0.05):
        super().__init__(table, batch_rows=batch_rows)
        self.delay_s = delay_s

    def execute(self, channel, lineage):
        time.sleep(self.delay_s)
        return super().execute(channel, lineage)


def _q(ctx, table, delay_s=None):
    ds = (InputArrowDataset(table, batch_rows=512) if delay_s is None
          else _SlowDS(table, delay_s=delay_s))
    return ctx.read_dataset(ds).groupby("k").agg_sql(
        "sum(v) as sv, count(*) as n")


def _ft_ctx():
    ctx = QuokkaContext()
    for k, v in FT_CFG.items():
        ctx.set_config(k, v)
    return ctx


def _sorted(df, by=("k",)):
    return df.sort_values(list(by)).reset_index(drop=True)


def _truth(table):
    return (table.to_pandas().groupby("k")
            .agg(sv=("v", "sum"), n=("v", "count")).reset_index())


def _exact(got, table):
    want = _truth(table)
    got = _sorted(got)[list(want.columns)]
    got = got.astype({c: want[c].dtype for c in want.columns})
    pd.testing.assert_frame_equal(got, want, check_exact=True)


def _no_namespace_rows(store: ControlStore, query_id: str) -> bool:
    for t in store.tables.values():
        if isinstance(t, set):
            if any(isinstance(m, tuple) and len(m) == 2 and m[0] == query_id
                   for m in t):
                return False
        elif any(isinstance(k, tuple) and len(k) == 2 and k[0] == query_id
                 for k in t):
            return False
    return all(not (isinstance(k, tuple) and query_id in k)
               for k in store.kv)


def _files_mentioning(root: str, query_id: str):
    hits = []
    for dirpath, _dirs, names in os.walk(root):
        hits += [os.path.join(dirpath, n) for n in names if query_id in n]
    return hits


class TestManifestRoundtrip:
    def test_known_answer_roundtrip_and_drift_is_loud(self, tmp_path):
        """The framed manifest is a stable known-answer format: what update
        writes, load returns field-for-field — and every drift axis (frame
        bytes, version, kind) fails loudly as ManifestMismatch."""
        m = {
            "version": bresume.MANIFEST_VERSION,
            "kind": "batch",
            "query_id": "q-known",
            "plan_fp": "ab12cd34ef56ab78",
            "written_at": 1234.5,
            "execs": {(1, 0): {"lct": (4, 7, 9), "ckpts": [(2, 3, 5)],
                               "irts": {4: {0: {0: 7}}},
                               "tape": [("exec", 0, [], True)],
                               "tape_base": 0}},
            "sinks": {(2, 0): 3},
            "est_bytes": 1 << 20,
            "plan_blob": b"opaque",
        }
        path = str(tmp_path / "batch-q-known.manifest")
        integrity.write_framed_atomic(path, pickle.dumps(m), site="manifest")
        assert bresume.load(path) == m

        # frame tamper: flip bytes in the middle of the payload
        raw = bytearray(open(path, "rb").read())
        raw[len(raw) // 2] ^= 0xFF
        bad = str(tmp_path / "batch-q-tamper.manifest")
        with open(bad, "wb") as f:
            f.write(bytes(raw))
        with pytest.raises(bresume.ManifestMismatch):
            bresume.load(bad)

        # version drift
        vdrift = str(tmp_path / "batch-q-vdrift.manifest")
        integrity.write_framed_atomic(
            vdrift, pickle.dumps({**m, "version": 999}), site="manifest")
        with pytest.raises(bresume.ManifestMismatch):
            bresume.load(vdrift)

        # a STREAM manifest is not resumable as a batch query
        sdrift = str(tmp_path / "batch-q-sdrift.manifest")
        integrity.write_framed_atomic(
            sdrift, pickle.dumps({**m, "kind": "stream"}), site="manifest")
        with pytest.raises(bresume.ManifestMismatch):
            bresume.load(sdrift)

    def test_durable_submit_writes_manifest_and_clean_finish_deletes(self):
        """Lifecycle hygiene: the manifest exists from submit (a crash
        before the first checkpoint still re-admits), tracks the real
        graph's fingerprint, and a clean finish deletes it — only process
        death leaves an orphan."""
        table = _small_table(seed=1)
        with QueryService(pool_size=2, exec_config=FT_CFG) as svc:
            h = svc.submit(_q(QuokkaContext(), table, delay_s=0.03),
                           durable=True)
            path = h.manifest_path
            assert path and os.path.exists(path)
            m = bresume.load(path)
            assert m["kind"] == "batch" and m["query_id"] == h.query_id
            assert m["plan_fp"] == bresume.structural_fingerprint(
                h._s.graph)
            # no checkpoint yet: an empty frontier re-admits as a fresh
            # run, but the plan payload must be there from the start
            assert m["plan_blob"]
            _exact(h.to_df(timeout=300), table)
            assert not os.path.exists(path), "clean finish must delete it"
            assert _no_namespace_rows(svc.store, h.query_id)


class TestSupervisor:
    def test_orphan_readmits_fifo_and_live_duplicate_refused(self, tmp_path):
        """An orphaned manifest re-admits through NORMAL admission — FIFO
        behind anything already queued, no barging — and resuming a query
        that is already LIVE in the service is refused loudly."""
        table = _small_table(seed=2)
        mb = 1 << 20
        # incarnation A: durable submit, snapshot the manifest as a crashed
        # process would have left it, then let A finish cleanly
        a_dir, b_dir = str(tmp_path / "a"), str(tmp_path / "b")
        orphan = str(tmp_path / "orphan.manifest")
        with QueryService(pool_size=2, exec_config=FT_CFG,
                          spill_dir=a_dir) as svc_a:
            h = svc_a.submit(_q(QuokkaContext(), table, delay_s=0.03),
                             durable=True, working_set_bytes=40 * mb)
            shutil.copy(h.manifest_path, orphan)
            orphan_qid = h.query_id
            _exact(h.to_df(timeout=300), table)
        # incarnation B: budget fits two 40 MiB queries; q3 queues FIRST,
        # then the orphan must line up BEHIND it
        with QueryService(pool_size=2, mem_budget=100 * mb,
                          admit_timeout=120, exec_config=FT_CFG,
                          spill_dir=b_dir) as svc:
            ckpt = os.path.join(svc._spill_dir, "ckpt")
            os.makedirs(ckpt, exist_ok=True)
            shutil.copy(orphan,
                        os.path.join(ckpt,
                                     f"batch-{orphan_qid}.manifest"))
            running = [svc.submit(_q(QuokkaContext(), table, delay_s=0.05),
                                  working_set_bytes=40 * mb)
                       for _ in range(2)]
            queued = svc.submit(_q(QuokkaContext(), table, delay_s=0.05),
                                working_set_bytes=40 * mb)
            st = svc.stats()["admission"]
            assert len(st["waiting"]) == 1, st
            before = obs.REGISTRY.counter("resume.orphans").value
            handles = svc.recover_orphans()
            assert [h.query_id for h in handles] == [orphan_qid]
            assert obs.REGISTRY.counter("resume.orphans").value \
                == before + 1
            waiting = [w[0] for w in svc.stats()["admission"]["waiting"]]
            assert waiting == [queued.query_id, orphan_qid], \
                "the orphan must not barge past already-queued work"
            assert handles[0].status == "queued"
            # duplicate resume of the LIVE orphan is refused loudly
            with pytest.raises(ValueError, match="already running"):
                svc.submit(_q(_ft_ctx(), table),
                           resume_from=handles[0].manifest_path)
            for h in running + [queued] + handles:
                _exact(h.to_df(timeout=300), table)
            assert svc.stats()["admission"]["used_bytes"] == 0
            assert _no_namespace_rows(svc.store, orphan_qid)

    def test_attach_cursor_drains_exactly_the_tail(self):
        """attach(query_id, cursor=...) seeds the delivery cursor: the
        first poll_batches drains exactly the batches the client has not
        durably captured — nothing re-surfaces, nothing is skipped."""
        table = _small_table(seed=3)
        with QueryService(pool_size=2, exec_config=FT_CFG) as svc:
            h = svc.submit(_q(QuokkaContext(), table), durable=True)
            h.wait(300)
            full = svc.attach(h.query_id).poll_batches()
            assert full, "a finished query must expose its batches"
            ch0, seq0, _t = full[0]
            tail = svc.attach(h.query_id,
                              cursor={ch0: seq0}).poll_batches()
            assert all(s > seq0 for c, s, _t in tail if c == ch0)
            assert ({(c, s) for c, s, _t in tail}
                    == {(c, s) for c, s, _t in full} - {(ch0, seq0)})
            # a fully caught-up cursor drains nothing
            done = {c: max(s for cc, s, _t in full if cc == c)
                    for c, _s, _t in full}
            assert svc.attach(h.query_id, cursor=done).poll_batches() == []


class TestCancelAndDeadline:
    def test_cancel_releases_bytes_and_leaves_zero_residue(self):
        table = _small_table(seed=4)
        with QueryService(pool_size=2, exec_config=FT_CFG) as svc:
            before = obs.REGISTRY.counter("cancel.requested").value
            h = svc.submit(_q(QuokkaContext(), table, delay_s=0.05),
                           durable=True, working_set_bytes=8 << 20)
            manifest = h.manifest_path
            deadline = time.time() + 30
            while h.status != "running" and time.time() < deadline:
                time.sleep(0.01)
            h.cancel(wait=True, timeout=60)
            with pytest.raises(QueryCancelled):
                h.result(timeout=60)
            assert obs.REGISTRY.counter("cancel.requested").value \
                > before
            assert svc.stats()["admission"]["used_bytes"] == 0
            assert _no_namespace_rows(svc.store, h.query_id)
            assert not os.path.exists(manifest)
            assert _files_mentioning(svc._spill_dir, h.query_id) == []

    def test_deadline_is_named_and_leaves_zero_residue(self):
        table = _small_table(seed=5)
        with QueryService(pool_size=2, exec_config=FT_CFG) as svc:
            before = obs.REGISTRY.counter("cancel.deadline").value
            h = svc.submit(_q(QuokkaContext(), table, delay_s=0.05),
                           durable=True, working_set_bytes=8 << 20,
                           deadline_s=0.4)
            manifest = h.manifest_path
            with pytest.raises(DeadlineExceeded):
                h.result(timeout=120)
            assert obs.REGISTRY.counter("cancel.deadline").value > before
            assert svc.stats()["admission"]["used_bytes"] == 0
            assert _no_namespace_rows(svc.store, h.query_id)
            assert not os.path.exists(manifest)
            assert _files_mentioning(svc._spill_dir, h.query_id) == []


class TestFingerprintStability:
    def test_structural_fingerprint_survives_pickled_relowering(self):
        """The QK025 pin: pickling the prepared plan and re-lowering it in
        a FRESH context/graph/store (what recover_orphans does after a
        restart) reproduces the submit-time structural fingerprint, and no
        part smuggles a memory address in."""
        table = _small_table(seed=6)
        qc = _ft_ctx()
        ds = _q(qc, table)
        sub, sink_id = qc._prepare_plan(ds.node_id)
        blob = pickle.dumps({"sub": sub, "sink_id": sink_id,
                             "exec_channels": qc.exec_channels})
        g0 = TaskGraph(qc.exec_config, store=ControlStore())
        qc._lower_plan(sub, sink_id, g0)
        fps = {bresume.structural_fingerprint(g0)}
        assert not any("0x" in p for p in bresume.structural_parts(g0))
        for _ in range(2):
            payload = pickle.loads(blob)
            ctx = QuokkaContext()
            ctx.exec_channels = payload["exec_channels"]
            g = TaskGraph(ctx.exec_config, store=ControlStore())
            ctx._lower_plan(payload["sub"], payload["sink_id"], g)
            fps.add(bresume.structural_fingerprint(g))
        assert len(fps) == 1, fps


class TestStartupJanitor:
    def test_unreadable_and_foreign_manifests_are_quarantined(self,
                                                              tmp_path):
        """recover_orphans never wedges on a bad manifest: unreadable bytes
        and a well-framed manifest with no plan payload are both moved to
        ``.corrupt`` and counted on resume.quarantined."""
        d = str(tmp_path / "ckpt")
        os.makedirs(d)
        junk = os.path.join(d, "batch-junk.manifest")
        with open(junk, "wb") as f:
            f.write(b"not a framed manifest at all")
        feed = os.path.join(d, "batch-feed.manifest")
        integrity.write_framed_atomic(feed, pickle.dumps({
            "version": bresume.MANIFEST_VERSION, "kind": "batch",
            "query_id": "q-feed", "plan_fp": "ab12cd34ef56ab78",
            "execs": {}, "sinks": {}, "est_bytes": None,
            "plan_blob": None,
        }), site="manifest")
        before = obs.REGISTRY.counter("resume.quarantined").value
        with QueryService(pool_size=1, exec_config=FT_CFG) as svc:
            assert svc.recover_orphans(manifest_dir=d) == []
        assert obs.REGISTRY.counter("resume.quarantined").value \
            == before + 2
        for p in (junk, feed):
            assert not os.path.exists(p) and os.path.exists(p + ".corrupt")
