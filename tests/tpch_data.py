"""Miniature TPC-H data generator (dbgen-alike, numpy-based).

Schemas and value domains follow the TPC-H spec closely enough that the
reference queries (apps/tpc-h/tpch.py shapes) select realistic fractions of
rows; correctness tests compare against pandas oracles computed on the same
generated data, so distribution fidelity only affects selectivity, not
correctness.
"""

import datetime

import numpy as np
import pandas as pd
import pyarrow as pa

EPOCH = datetime.date(1970, 1, 1)


def _dates(r, n, lo="1992-01-01", hi="1998-12-01"):
    lo_d = (datetime.date.fromisoformat(lo) - EPOCH).days
    hi_d = (datetime.date.fromisoformat(hi) - EPOCH).days
    return r.integers(lo_d, hi_d, n).astype(np.int32)


REGIONS = ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]
NATIONS = [
    ("ALGERIA", 0), ("ARGENTINA", 1), ("BRAZIL", 1), ("CANADA", 1), ("EGYPT", 4),
    ("ETHIOPIA", 0), ("FRANCE", 3), ("GERMANY", 3), ("INDIA", 2), ("INDONESIA", 2),
    ("IRAN", 4), ("IRAQ", 4), ("JAPAN", 2), ("JORDAN", 4), ("KENYA", 0),
    ("MOROCCO", 0), ("MOZAMBIQUE", 0), ("PERU", 1), ("CHINA", 2), ("ROMANIA", 3),
    ("SAUDI ARABIA", 4), ("VIETNAM", 2), ("RUSSIA", 3), ("UNITED KINGDOM", 3),
    ("UNITED STATES", 1),
]
SEGMENTS = ["AUTOMOBILE", "BUILDING", "FURNITURE", "MACHINERY", "HOUSEHOLD"]
PRIORITIES = ["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED", "5-LOW"]
SHIPMODES = ["REG AIR", "AIR", "RAIL", "SHIP", "TRUCK", "MAIL", "FOB"]
INSTRUCTS = ["DELIVER IN PERSON", "COLLECT COD", "NONE", "TAKE BACK RETURN"]
TYPES = [
    f"{a} {b} {c}"
    for a in ["STANDARD", "SMALL", "MEDIUM", "LARGE", "ECONOMY", "PROMO"]
    for b in ["ANODIZED", "BURNISHED", "PLATED", "POLISHED", "BRUSHED"]
    for c in ["TIN", "NICKEL", "BRASS", "STEEL", "COPPER"]
]
CONTAINERS = [
    f"{a} {b}"
    for a in ["SM", "LG", "MED", "JUMBO", "WRAP"]
    for b in ["CASE", "BOX", "BAG", "JAR", "PKG", "PACK", "CAN", "DRUM"]
]


def generate(sf: float = 0.003, seed: int = 0, skew: bool = False,
             nulls: bool = False):
    """Return {table_name: pyarrow.Table}.  sf=1 would be full TPC-H scale.
    skew: Zipf-distributed foreign keys (hot customers/parts — exercises
    rank-join/segment-agg paths with giant groups).  nulls: ~3% nulls in
    lineitem numeric/string columns (null-semantics under real queries)."""
    r = np.random.default_rng(seed)

    def fk(n_draw, lo, hi):
        """Foreign keys in [lo, hi): uniform, or Zipf-skewed when requested."""
        if not skew:
            return r.integers(lo, hi, n_draw).astype(np.int64)
        z = r.zipf(1.3, n_draw).astype(np.int64)
        return lo + (z - 1) % (hi - lo)

    def with_nulls(arr, frac=0.03):
        if not nulls:
            return arr
        mask = r.random(len(arr)) < frac
        return pa.array(
            [None if m else v for v, m in zip(arr.tolist() if hasattr(arr, "tolist") else arr, mask)]
        )
    n_orders = max(int(1_500_000 * sf), 50)
    n_cust = max(int(150_000 * sf), 20)
    n_part = max(int(200_000 * sf), 25)
    n_supp = max(int(10_000 * sf), 10)
    n_nation = 25

    region = pa.table(
        {
            "r_regionkey": np.arange(5, dtype=np.int64),
            "r_name": REGIONS,
            "r_comment": [f"region {i}" for i in range(5)],
        }
    )
    nation = pa.table(
        {
            "n_nationkey": np.arange(n_nation, dtype=np.int64),
            "n_name": [n for n, _ in NATIONS],
            "n_regionkey": np.array([rg for _, rg in NATIONS], dtype=np.int64),
            "n_comment": [f"nation {i}" for i in range(n_nation)],
        }
    )
    supplier = pa.table(
        {
            "s_suppkey": np.arange(1, n_supp + 1, dtype=np.int64),
            "s_name": [f"Supplier#{i:09d}" for i in range(1, n_supp + 1)],
            "s_address": [f"addr {i}" for i in range(n_supp)],
            "s_nationkey": r.integers(0, n_nation, n_supp).astype(np.int64),
            "s_phone": [f"{r.integers(10,35)}-{i:07d}" for i in range(n_supp)],
            "s_acctbal": np.round(r.uniform(-999, 9999, n_supp), 2),
            "s_comment": [
                ("Customer Complaints" if r.random() < 0.02 else f"supp comment {i}")
                for i in range(n_supp)
            ],
        }
    )
    part = pa.table(
        {
            "p_partkey": np.arange(1, n_part + 1, dtype=np.int64),
            "p_name": [
                f"{r.choice(['tomato','blue','green','red','ivory','forest'])} "
                f"{r.choice(['metallic','polished','sandy','spring','misty'])} part{i}"
                for i in range(n_part)
            ],
            "p_mfgr": [f"Manufacturer#{r.integers(1,6)}" for _ in range(n_part)],
            "p_brand": [f"Brand#{r.integers(1,6)}{r.integers(1,6)}" for _ in range(n_part)],
            "p_type": [TYPES[i] for i in r.integers(0, len(TYPES), n_part)],
            "p_size": r.integers(1, 51, n_part).astype(np.int64),
            "p_container": [CONTAINERS[i] for i in r.integers(0, len(CONTAINERS), n_part)],
            "p_retailprice": np.round(900 + r.uniform(0, 1200, n_part), 2),
            "p_comment": [f"part comment {i}" for i in range(n_part)],
        }
    )
    n_ps = n_part * 4
    partsupp = pa.table(
        {
            "ps_partkey": np.repeat(np.arange(1, n_part + 1, dtype=np.int64), 4),
            "ps_suppkey": (
                (np.repeat(np.arange(0, n_part, dtype=np.int64), 4)
                 + np.tile(np.arange(4, dtype=np.int64) * (n_supp // 4 + 1), n_part))
                % n_supp + 1
            ),
            "ps_availqty": r.integers(1, 10000, n_ps).astype(np.int64),
            "ps_supplycost": np.round(r.uniform(1, 1000, n_ps), 2),
            "ps_comment": [f"ps comment {i}" for i in range(n_ps)],
        }
    )
    customer = pa.table(
        {
            "c_custkey": np.arange(1, n_cust + 1, dtype=np.int64),
            "c_name": [f"Customer#{i:09d}" for i in range(1, n_cust + 1)],
            "c_address": [f"caddr {i}" for i in range(n_cust)],
            "c_nationkey": r.integers(0, n_nation, n_cust).astype(np.int64),
            "c_phone": [
                f"{k}-{r.integers(100,999)}-{r.integers(100,999)}-{r.integers(1000,9999)}"
                for k in r.integers(10, 35, n_cust)
            ],
            "c_acctbal": np.round(r.uniform(-999, 9999, n_cust), 2),
            "c_mktsegment": [SEGMENTS[i] for i in r.integers(0, 5, n_cust)],
            "c_comment": [f"cust comment {i}" for i in range(n_cust)],
        }
    )
    o_orderdate = _dates(r, n_orders, "1992-01-01", "1998-08-02")
    # dbgen-alike: customers with custkey % 3 == 0 place no orders (this is
    # what makes Q22's "customers without orders" anti-join non-empty)
    with_orders = np.arange(1, n_cust + 1, dtype=np.int64)
    with_orders = with_orders[with_orders % 3 != 0]
    orders = pa.table(
        {
            "o_orderkey": np.arange(1, n_orders + 1, dtype=np.int64) * 4,
            "o_custkey": with_orders[fk(n_orders, 0, len(with_orders))],
            "o_orderstatus": [["F", "O", "P"][i] for i in r.integers(0, 3, n_orders)],
            "o_totalprice": np.round(r.uniform(1000, 400000, n_orders), 2),
            "o_orderdate": pa.array(o_orderdate, type=pa.int32()).cast(pa.date32()),
            "o_orderpriority": [PRIORITIES[i] for i in r.integers(0, 5, n_orders)],
            "o_clerk": [f"Clerk#{r.integers(1,1000):09d}" for _ in range(n_orders)],
            "o_shippriority": np.zeros(n_orders, dtype=np.int64),
            "o_comment": [
                ("special requests" if r.random() < 0.05 else f"order comment {i}")
                for i in range(n_orders)
            ],
        }
    )
    # lineitem: 1-7 lines per order
    lines_per = r.integers(1, 8, n_orders)
    n_li = int(lines_per.sum())
    l_orderkey = np.repeat(orders.column("o_orderkey").to_numpy(), lines_per)
    l_linenumber = np.concatenate([np.arange(1, k + 1) for k in lines_per]).astype(np.int64)
    odate = np.repeat(o_orderdate, lines_per)
    l_shipdate = odate + r.integers(1, 122, n_li)
    l_commitdate = odate + r.integers(30, 91, n_li)
    l_receiptdate = l_shipdate + r.integers(1, 31, n_li)
    qty = r.integers(1, 51, n_li).astype(np.float64)
    price = np.round(qty * (900 + r.uniform(0, 1200, n_li)) / 10, 2)
    lineitem = pa.table(
        {
            "l_orderkey": l_orderkey,
            "l_partkey": fk(n_li, 1, n_part + 1),
            "l_suppkey": fk(n_li, 1, n_supp + 1),
            "l_linenumber": l_linenumber,
            "l_quantity": qty,
            "l_extendedprice": price,
            "l_discount": with_nulls(np.round(r.uniform(0, 0.1, n_li), 2)),
            "l_tax": with_nulls(np.round(r.uniform(0, 0.08, n_li), 2)),
            "l_returnflag": with_nulls(
                np.array([["A", "N", "R"][i] for i in r.integers(0, 3, n_li)], dtype=object)
            ),
            "l_linestatus": [["F", "O"][i] for i in r.integers(0, 2, n_li)],
            "l_shipdate": pa.array(l_shipdate.astype(np.int32), type=pa.int32()).cast(pa.date32()),
            "l_commitdate": pa.array(l_commitdate.astype(np.int32), type=pa.int32()).cast(pa.date32()),
            "l_receiptdate": pa.array(l_receiptdate.astype(np.int32), type=pa.int32()).cast(pa.date32()),
            "l_shipinstruct": [INSTRUCTS[i] for i in r.integers(0, 4, n_li)],
            "l_shipmode": [SHIPMODES[i] for i in r.integers(0, 7, n_li)],
            "l_comment": [f"li comment {i}" for i in range(n_li)],
        }
    )
    return {
        "region": region,
        "nation": nation,
        "supplier": supplier,
        "part": part,
        "partsupp": partsupp,
        "customer": customer,
        "orders": orders,
        "lineitem": lineitem,
    }


def write_parquet_dir(tables, root, row_group_size: int = 4096):
    import os

    import pyarrow.parquet as pq

    paths = {}
    for name, t in tables.items():
        p = os.path.join(root, f"{name}.parquet")
        pq.write_table(t, p, row_group_size=row_group_size)
        paths[name] = p
    return paths
