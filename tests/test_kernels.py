"""Unit tests for the device columnar layer (bridge + kernels + joins),
with pandas as the correctness oracle (replacing the reference's
eyeball-vs-DuckDB strategy, SURVEY.md section 4)."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from quokka_tpu.ops import bridge, kernels
from quokka_tpu.ops import join as join_ops
from quokka_tpu.ops.batch import DeviceBatch, NumCol, StrCol

from conftest import make_table


def roundtrip(table):
    return bridge.device_to_arrow(bridge.arrow_to_device(table))


class TestBridge:
    def test_roundtrip_mixed(self, table):
        out = roundtrip(table)
        assert out.num_rows == table.num_rows
        pd.testing.assert_frame_equal(
            out.to_pandas(), table.to_pandas(), check_dtype=False
        )

    def test_roundtrip_empty(self):
        t = pa.table({"a": pa.array([], type=pa.int64())})
        out = roundtrip(t)
        assert out.num_rows == 0

    def test_strings_dictionary(self, table):
        b = bridge.arrow_to_device(table)
        s = b.columns["s"]
        assert isinstance(s, StrCol)
        assert len(s.dictionary) <= 4

    def test_wide_int_limbs(self):
        vals = np.array([2**40, -(2**40), 5, -5, 0, 2**62], dtype=np.int64)
        import jax

        was = jax.config.read("jax_enable_x64")
        jax.config.update("jax_enable_x64", False)
        try:
            t = pa.table({"a": vals})
            b = bridge.arrow_to_device(t)
            assert b.columns["a"].hi is not None
            out = bridge.device_to_arrow(b)
            np.testing.assert_array_equal(out.column("a").to_numpy(), vals)
            # limb sort order == numeric order
            s = kernels.sort_batch(b, ["a"])
            out2 = bridge.device_to_arrow(s)
            np.testing.assert_array_equal(out2.column("a").to_numpy(), np.sort(vals))
        finally:
            jax.config.update("jax_enable_x64", was)

    def test_concat_batches_merges_dicts(self):
        t1 = pa.table({"s": ["a", "b"], "x": [1.0, 2.0]})
        t2 = pa.table({"s": ["b", "c"], "x": [3.0, 4.0]})
        b = bridge.concat_batches([bridge.arrow_to_device(t1), bridge.arrow_to_device(t2)])
        out = bridge.device_to_arrow(b).to_pandas().sort_values("x").reset_index(drop=True)
        assert list(out["s"]) == ["a", "b", "b", "c"]


class TestKernels:
    def test_filter_compact(self, table, pdf):
        b = bridge.arrow_to_device(table)
        mask = b.columns["q"].data > 25
        f = kernels.compact(kernels.apply_mask(b, mask))
        expect = pdf[pdf.q > 25]
        assert f.count_valid() == len(expect)
        got = bridge.device_to_arrow(f).to_pandas()
        pd.testing.assert_frame_equal(
            got.reset_index(drop=True), expect.reset_index(drop=True), check_dtype=False
        )

    def test_groupby_sum_count(self, table, pdf):
        b = bridge.arrow_to_device(table)
        g = kernels.groupby_aggregate(
            b,
            ["k"],
            [
                ("v_sum", "sum", b.columns["v"].data),
                ("n", "count", None),
                ("q_max", "max", b.columns["q"].data),
            ],
        )
        got = (
            bridge.device_to_arrow(kernels.compact(g))
            .to_pandas()
            .sort_values("k")
            .reset_index(drop=True)
        )
        exp = (
            pdf.groupby("k")
            .agg(v_sum=("v", "sum"), n=("v", "size"), q_max=("q", "max"))
            .reset_index()
        )
        pd.testing.assert_frame_equal(got, exp, check_dtype=False, rtol=1e-9)

    def test_groupby_string_key(self, table, pdf):
        b = bridge.arrow_to_device(table)
        g = kernels.groupby_aggregate(b, ["s"], [("n", "count", None)])
        got = (
            bridge.device_to_arrow(kernels.compact(g))
            .to_pandas()
            .sort_values("s")
            .reset_index(drop=True)
        )
        exp = pdf.groupby("s").size().reset_index(name="n")
        pd.testing.assert_frame_equal(got, exp, check_dtype=False)

    def test_groupby_multi_key(self, table, pdf):
        b = bridge.arrow_to_device(table)
        g = kernels.groupby_aggregate(b, ["k", "s"], [("v_min", "min", b.columns["v"].data)])
        got = (
            bridge.device_to_arrow(kernels.compact(g))
            .to_pandas()
            .sort_values(["k", "s"])
            .reset_index(drop=True)
        )
        exp = pdf.groupby(["k", "s"]).agg(v_min=("v", "min")).reset_index()
        pd.testing.assert_frame_equal(got, exp, check_dtype=False, rtol=1e-9)

    def test_groupby_no_keys(self, table, pdf):
        b = bridge.arrow_to_device(table)
        g = kernels.groupby_aggregate(b, [], [("t", "sum", b.columns["v"].data)])
        got = bridge.device_to_arrow(kernels.compact(g)).to_pandas()
        assert len(got) == 1
        np.testing.assert_allclose(got["t"][0], pdf.v.sum(), rtol=1e-9)

    def test_sort_multi(self, table, pdf):
        b = bridge.arrow_to_device(table)
        s = kernels.sort_batch(b, ["k", "v"], [False, True])
        got = bridge.device_to_arrow(s).to_pandas().reset_index(drop=True)
        exp = pdf.sort_values(["k", "v"], ascending=[True, False]).reset_index(drop=True)
        pd.testing.assert_frame_equal(got, exp, check_dtype=False)

    def test_sort_string_lexicographic(self, table, pdf):
        b = bridge.arrow_to_device(table)
        s = kernels.sort_batch(b, ["s"])
        got = bridge.device_to_arrow(s).to_pandas()["s"].tolist()
        assert got == sorted(pdf.s.tolist())

    def test_top_k(self, table, pdf):
        b = bridge.arrow_to_device(table)
        t = kernels.top_k(b, ["v"], 7, [True])
        got = bridge.device_to_arrow(t).to_pandas()["v"].to_numpy()
        exp = pdf.v.nlargest(7).to_numpy()
        np.testing.assert_allclose(got, exp)

    def test_distinct(self, table, pdf):
        b = bridge.arrow_to_device(table)
        d = kernels.distinct(b, ["k", "s"])
        got = bridge.device_to_arrow(kernels.compact(d)).to_pandas()
        exp = pdf[["k", "s"]].drop_duplicates()
        assert len(got) == len(exp)

    def test_partition_deterministic_and_complete(self, table):
        b = bridge.arrow_to_device(table)
        pids = kernels.partition_ids(b, ["k"], 4)
        parts = kernels.split_by_partition(b, pids, 4)
        assert sum(p.count_valid() for p in parts) == b.count_valid()
        # same key always lands in the same partition
        k = np.asarray(b.columns["k"].data)[np.asarray(b.valid)]
        pid = np.asarray(pids)[np.asarray(b.valid)]
        df = pd.DataFrame({"k": k, "p": pid})
        assert (df.groupby("k").p.nunique() == 1).all()

    def test_head(self, table):
        b = bridge.arrow_to_device(table)
        h = kernels.head(b, 10)
        assert h.count_valid() == 10


def _join_oracle(ldf, rdf, on, how):
    return ldf.merge(rdf, on=on, how=how)


class TestJoins:
    def setup_method(self):
        r = np.random.default_rng(7)
        self.left = pa.table(
            {
                "key": r.integers(0, 50, 300).astype(np.int64),
                "lv": r.normal(size=300),
            }
        )
        # unique build side (PK)
        self.right_pk = pa.table(
            {
                "key": np.arange(0, 40, dtype=np.int64),
                "rv": r.normal(size=40),
            }
        )
        # duplicated build side
        self.right_mm = pa.table(
            {
                "key": r.integers(0, 30, 80).astype(np.int64),
                "rv": r.normal(size=80),
            }
        )

    def test_pk_inner(self):
        lb = bridge.arrow_to_device(self.left)
        rb = bridge.arrow_to_device(self.right_pk)
        out = join_ops.hash_join_pk(lb, rb, ["key"], ["key"], "inner", ["rv"])
        got = (
            bridge.device_to_arrow(kernels.compact(out))
            .to_pandas()
            .sort_values(["key", "lv"])
            .reset_index(drop=True)
        )
        exp = (
            _join_oracle(self.left.to_pandas(), self.right_pk.to_pandas(), "key", "inner")
            .sort_values(["key", "lv"])
            .reset_index(drop=True)
        )
        pd.testing.assert_frame_equal(got[exp.columns.tolist()], exp, check_dtype=False)

    def test_pk_semi_anti(self):
        lb = bridge.arrow_to_device(self.left)
        rb = bridge.arrow_to_device(self.right_pk)
        semi = join_ops.hash_join_pk(lb, rb, ["key"], ["key"], "semi")
        anti = join_ops.hash_join_pk(lb, rb, ["key"], ["key"], "anti")
        ldf = self.left.to_pandas()
        keys = set(self.right_pk.to_pandas().key)
        assert kernels.compact(semi).count_valid() == int(ldf.key.isin(keys).sum())
        assert kernels.compact(anti).count_valid() == int((~ldf.key.isin(keys)).sum())

    def test_mm_inner(self):
        lb = bridge.arrow_to_device(self.left)
        rb = bridge.arrow_to_device(self.right_mm)
        out = join_ops.hash_join_general(lb, rb, ["key"], ["key"], "inner", ["rv"])
        got = (
            bridge.device_to_arrow(kernels.compact(out))
            .to_pandas()
            .sort_values(["key", "lv", "rv"])
            .reset_index(drop=True)
        )
        exp = (
            _join_oracle(self.left.to_pandas(), self.right_mm.to_pandas(), "key", "inner")
            .sort_values(["key", "lv", "rv"])
            .reset_index(drop=True)
        )
        pd.testing.assert_frame_equal(got[exp.columns.tolist()], exp, check_dtype=False)

    def test_mm_left_count(self):
        lb = bridge.arrow_to_device(self.left)
        rb = bridge.arrow_to_device(self.right_mm)
        out = join_ops.hash_join_general(lb, rb, ["key"], ["key"], "left", ["rv"])
        exp = _join_oracle(self.left.to_pandas(), self.right_mm.to_pandas(), "key", "left")
        assert kernels.compact(out).count_valid() == len(exp)

    def test_string_key_join(self):
        l = pa.table({"s": ["a", "b", "c", "a"], "x": [1.0, 2.0, 3.0, 4.0]})
        r_ = pa.table({"s": ["a", "c"], "y": [10.0, 30.0]})
        out = join_ops.hash_join_pk(
            bridge.arrow_to_device(l), bridge.arrow_to_device(r_), ["s"], ["s"], "inner", ["y"]
        )
        got = (
            bridge.device_to_arrow(kernels.compact(out))
            .to_pandas()
            .sort_values("x")
            .reset_index(drop=True)
        )
        assert got.y.tolist() == [10.0, 30.0, 10.0]

    def test_build_unique_check(self):
        rb = bridge.arrow_to_device(self.right_pk)
        mb = bridge.arrow_to_device(self.right_mm)
        assert join_ops.build_keys_unique(rb, ["key"])
        assert not join_ops.build_keys_unique(mb, ["key"])
