"""TPC-H Q2/Q7/Q8/Q9/Q11/Q15/Q16/Q17/Q18/Q20/Q21/Q22 vs pandas oracles.

Correlated and EXISTS subqueries are rewritten dataframe-style — aggregate +
join-back, semi/anti joins, broadcast scalars — the same rewrites the
reference codes by hand in apps/tpc-h/tpch.py:78-560.  Completes the 22-query
coverage started in test_tpch.py (VERDICT r1 item 4)."""

import datetime

import numpy as np
import pandas as pd
import pytest

from quokka_tpu import QuokkaContext

import tpch_data


@pytest.fixture(scope="module")
def env(tmp_path_factory):
    root = tmp_path_factory.mktemp("tpch2")
    tables = tpch_data.generate(sf=0.003, seed=11)
    paths = tpch_data.write_parquet_dir(tables, str(root))
    ctx = QuokkaContext(io_channels=2, exec_channels=2)
    dfs = {k: t.to_pandas() for k, t in tables.items()}
    return ctx, paths, dfs


def streams(env):
    ctx, paths, _ = env
    return {name: ctx.read_parquet(p) for name, p in paths.items()}


def sorted_eq(got, exp, by, rtol=1e-8):
    got = got.sort_values(by).reset_index(drop=True)[list(exp.columns)]
    exp = exp.sort_values(by).reset_index(drop=True)
    pd.testing.assert_frame_equal(got, exp, check_dtype=False, rtol=rtol)


def test_q2(env):
    ctx, paths, dfs = env
    s = streams(env)
    # EUROPE partsupp universe: partsupp x supplier x nation x region
    nat_eu = s["nation"].join(
        s["region"].filter_sql("r_name = 'EUROPE'"),
        left_on="n_regionkey", right_on="r_regionkey", how="semi",
    )
    ps_eu = (
        s["partsupp"]
        .join(s["supplier"], left_on="ps_suppkey", right_on="s_suppkey")
        .join(nat_eu, left_on="s_nationkey", right_on="n_nationkey")
    )
    # correlated min(ps_supplycost) per part -> aggregate + join back
    minc = ps_eu.groupby("ps_partkey").agg_sql("min(ps_supplycost) as min_cost")
    p = s["part"].filter_sql("p_size = 15 and p_type like '%BRASS'")
    got = (
        ps_eu.join(p, left_on="ps_partkey", right_on="p_partkey")
        .join(minc.rename({"ps_partkey": "mc_partkey"}),
              left_on="ps_partkey", right_on="mc_partkey")
        .filter_sql("ps_supplycost = min_cost")
        .select(["s_acctbal", "s_name", "n_name", "ps_partkey", "p_mfgr"])
        .collect()
    )
    n, r, su, ps, pt = (dfs[k] for k in ("nation", "region", "supplier", "partsupp", "part"))
    eu = n.merge(r[r.r_name == "EUROPE"], left_on="n_regionkey", right_on="r_regionkey")
    pse = ps.merge(su, left_on="ps_suppkey", right_on="s_suppkey").merge(
        eu, left_on="s_nationkey", right_on="n_nationkey"
    )
    mc = pse.groupby("ps_partkey").ps_supplycost.min().reset_index(name="min_cost")
    pf = pt[(pt.p_size == 15) & pt.p_type.str.endswith("BRASS")]
    exp = (
        pse.merge(pf, left_on="ps_partkey", right_on="p_partkey")
        .merge(mc, on="ps_partkey")
    )
    exp = exp[exp.ps_supplycost == exp.min_cost][
        ["s_acctbal", "s_name", "n_name", "ps_partkey", "p_mfgr"]
    ]
    assert len(exp) > 0
    sorted_eq(got, exp, by=["ps_partkey", "s_name"])


def test_q7(env):
    ctx, paths, dfs = env
    s = streams(env)
    n1 = s["nation"].rename({"n_name": "supp_nation", "n_nationkey": "n1key"})
    n2 = s["nation"].rename({"n_name": "cust_nation", "n_nationkey": "n2key"})
    got = (
        s["lineitem"]
        .filter_sql("l_shipdate between date '1995-01-01' and date '1996-12-31'")
        .join(s["supplier"], left_on="l_suppkey", right_on="s_suppkey")
        .join(s["orders"], left_on="l_orderkey", right_on="o_orderkey")
        .join(s["customer"], left_on="o_custkey", right_on="c_custkey")
        .join(n1.select(["supp_nation", "n1key"]), left_on="s_nationkey", right_on="n1key")
        .join(n2.select(["cust_nation", "n2key"]), left_on="c_nationkey", right_on="n2key")
        .filter_sql(
            "(supp_nation = 'FRANCE' and cust_nation = 'GERMANY') or "
            "(supp_nation = 'GERMANY' and cust_nation = 'FRANCE')"
        )
        .with_columns_sql(
            "extract(year from l_shipdate) as l_year, "
            "l_extendedprice * (1 - l_discount) as volume"
        )
        .groupby(["supp_nation", "cust_nation", "l_year"])
        .agg_sql("sum(volume) as revenue")
        .collect()
    )
    l, su, o, c, n = (dfs[k] for k in ("lineitem", "supplier", "orders", "customer", "nation"))
    f = l[(l.l_shipdate >= datetime.date(1995, 1, 1)) & (l.l_shipdate <= datetime.date(1996, 12, 31))]
    j = (
        f.merge(su, left_on="l_suppkey", right_on="s_suppkey")
        .merge(o, left_on="l_orderkey", right_on="o_orderkey")
        .merge(c, left_on="o_custkey", right_on="c_custkey")
        .merge(n.rename(columns={"n_name": "supp_nation"}), left_on="s_nationkey", right_on="n_nationkey")
        .merge(n.rename(columns={"n_name": "cust_nation"}), left_on="c_nationkey", right_on="n_nationkey")
    )
    j = j[((j.supp_nation == "FRANCE") & (j.cust_nation == "GERMANY"))
          | ((j.supp_nation == "GERMANY") & (j.cust_nation == "FRANCE"))]
    assert len(j) > 0
    j = j.assign(
        l_year=pd.to_datetime(j.l_shipdate).dt.year,
        volume=j.l_extendedprice * (1 - j.l_discount),
    )
    exp = (
        j.groupby(["supp_nation", "cust_nation", "l_year"])
        .volume.sum().reset_index(name="revenue")
    )
    sorted_eq(got, exp, by=["supp_nation", "cust_nation", "l_year"])


def test_q8(env):
    ctx, paths, dfs = env
    s = streams(env)
    nat_am = s["nation"].join(
        s["region"].filter_sql("r_name = 'AMERICA'"),
        left_on="n_regionkey", right_on="r_regionkey", how="semi",
    )
    n2 = s["nation"].rename({"n_name": "supp_nation", "n_nationkey": "n2key"})
    got = (
        s["lineitem"]
        .join(s["part"].filter_sql("p_type = 'ECONOMY ANODIZED STEEL'"),
              left_on="l_partkey", right_on="p_partkey", how="semi")
        .join(s["orders"].filter_sql(
            "o_orderdate between date '1995-01-01' and date '1996-12-31'"),
            left_on="l_orderkey", right_on="o_orderkey")
        .join(s["customer"], left_on="o_custkey", right_on="c_custkey")
        .join(nat_am, left_on="c_nationkey", right_on="n_nationkey", how="semi")
        .join(s["supplier"], left_on="l_suppkey", right_on="s_suppkey")
        .join(n2.select(["supp_nation", "n2key"]), left_on="s_nationkey", right_on="n2key")
        .with_columns_sql(
            "extract(year from o_orderdate) as o_year, "
            "l_extendedprice * (1 - l_discount) as volume, "
            "case when supp_nation = 'BRAZIL' then l_extendedprice * (1 - l_discount) "
            "else 0.0 end as brazil_volume"
        )
        .groupby("o_year")
        .agg_sql("sum(brazil_volume) / sum(volume) as mkt_share")
        .collect()
    )
    l, pt, o, c, su, n, r = (dfs[k] for k in
                             ("lineitem", "part", "orders", "customer", "supplier", "nation", "region"))
    am_keys = n.merge(r[r.r_name == "AMERICA"], left_on="n_regionkey",
                      right_on="r_regionkey").n_nationkey
    pk = pt[pt.p_type == "ECONOMY ANODIZED STEEL"].p_partkey
    f = l[l.l_partkey.isin(pk)]
    j = (
        f.merge(o[(o.o_orderdate >= datetime.date(1995, 1, 1))
                  & (o.o_orderdate <= datetime.date(1996, 12, 31))],
                left_on="l_orderkey", right_on="o_orderkey")
        .merge(c[c.c_nationkey.isin(am_keys)], left_on="o_custkey", right_on="c_custkey")
        .merge(su, left_on="l_suppkey", right_on="s_suppkey")
        .merge(n.rename(columns={"n_name": "supp_nation"}),
               left_on="s_nationkey", right_on="n_nationkey")
    )
    assert len(j) > 0
    j = j.assign(
        o_year=pd.to_datetime(j.o_orderdate).dt.year,
        volume=j.l_extendedprice * (1 - j.l_discount),
    )
    j["brazil_volume"] = np.where(j.supp_nation == "BRAZIL", j.volume, 0.0)
    g = j.groupby("o_year").agg(bv=("brazil_volume", "sum"), v=("volume", "sum"))
    exp = (g.bv / g.v).reset_index(name="mkt_share")
    sorted_eq(got, exp, by=["o_year"])


def test_q9(env):
    ctx, paths, dfs = env
    s = streams(env)
    got = (
        s["lineitem"]
        .join(s["part"].filter_sql("p_name like '%green%'"),
              left_on="l_partkey", right_on="p_partkey", how="semi")
        .join(s["partsupp"], left_on=["l_partkey", "l_suppkey"],
              right_on=["ps_partkey", "ps_suppkey"])
        .join(s["supplier"], left_on="l_suppkey", right_on="s_suppkey")
        .join(s["nation"], left_on="s_nationkey", right_on="n_nationkey")
        .join(s["orders"], left_on="l_orderkey", right_on="o_orderkey")
        .with_columns_sql(
            "extract(year from o_orderdate) as o_year, "
            "l_extendedprice * (1 - l_discount) - ps_supplycost * l_quantity as amount"
        )
        .groupby(["n_name", "o_year"])
        .agg_sql("sum(amount) as sum_profit")
        .collect()
    )
    l, pt, ps, su, n, o = (dfs[k] for k in
                           ("lineitem", "part", "partsupp", "supplier", "nation", "orders"))
    pk = pt[pt.p_name.str.contains("green")].p_partkey
    j = (
        l[l.l_partkey.isin(pk)]
        .merge(ps, left_on=["l_partkey", "l_suppkey"], right_on=["ps_partkey", "ps_suppkey"])
        .merge(su, left_on="l_suppkey", right_on="s_suppkey")
        .merge(n, left_on="s_nationkey", right_on="n_nationkey")
        .merge(o, left_on="l_orderkey", right_on="o_orderkey")
    )
    assert len(j) > 0
    j = j.assign(
        o_year=pd.to_datetime(j.o_orderdate).dt.year,
        amount=j.l_extendedprice * (1 - j.l_discount) - j.ps_supplycost * j.l_quantity,
    )
    exp = j.groupby(["n_name", "o_year"]).amount.sum().reset_index(name="sum_profit")
    sorted_eq(got, exp, by=["n_name", "o_year"])


def test_q11(env):
    ctx, paths, dfs = env
    s = streams(env)
    ps, su, n = (dfs[k] for k in ("partsupp", "supplier", "nation"))
    # spec names GERMANY; use the modal supplier nation for the mini dataset
    nat_key = int(su.s_nationkey.mode()[0])
    nat_name = n[n.n_nationkey == nat_key].n_name.iloc[0]
    ps_de = (
        s["partsupp"]
        .join(s["supplier"], left_on="ps_suppkey", right_on="s_suppkey")
        .join(s["nation"].filter_sql(f"n_name = '{nat_name}'"),
              left_on="s_nationkey", right_on="n_nationkey", how="semi")
        .with_columns_sql("ps_supplycost * ps_availqty as value")
    )
    de = su[su.s_nationkey == nat_key]
    j = ps[ps.ps_suppkey.isin(de.s_suppkey)]
    j = j.assign(value=j.ps_supplycost * j.ps_availqty)
    assert len(j) > 0
    g = j.groupby("ps_partkey").value.sum().reset_index()
    # spec uses fraction 0.0001/SF of the total; the mini dataset is too small
    # for that to select anything, so threshold at the oracle's 80th pctile —
    # same cutoff on both sides, still exercising scalar-subquery-as-literal
    cutoff = float(g.value.quantile(0.8))
    got = (
        ps_de.groupby("ps_partkey")
        .agg_sql("sum(value) as value")
        .filter_sql(f"value > {cutoff}")
        .collect()
    )
    exp = g[g.value > cutoff]
    assert len(exp) > 0
    sorted_eq(got, exp, by=["ps_partkey"])


def test_q15(env):
    ctx, paths, dfs = env
    s = streams(env)
    rev = (
        s["lineitem"]
        .filter_sql("l_shipdate >= date '1996-01-01' and l_shipdate < date '1996-01-01' + interval '3' month")
        .with_columns_sql("l_extendedprice * (1 - l_discount) as v")
        .groupby("l_suppkey")
        .agg_sql("sum(v) as total_revenue")
    )
    top = float(rev.agg_sql("max(total_revenue) as m").collect().m[0])
    got = (
        rev.filter_sql(f"total_revenue >= {top}")
        .join(s["supplier"], left_on="l_suppkey", right_on="s_suppkey")
        .select(["l_suppkey", "s_name", "total_revenue"])
        .collect()
    )
    l, su = dfs["lineitem"], dfs["supplier"]
    f = l[(l.l_shipdate >= datetime.date(1996, 1, 1)) & (l.l_shipdate < datetime.date(1996, 4, 1))]
    g = (f.l_extendedprice * (1 - f.l_discount)).groupby(f.l_suppkey).sum().rename("total_revenue")
    assert len(g) > 0
    winners = g[g == g.max()].reset_index()
    assert len(got) == len(winners) >= 1
    np.testing.assert_allclose(
        sorted(got.total_revenue), sorted(winners.total_revenue), rtol=1e-6
    )
    assert set(got.l_suppkey) == set(winners.l_suppkey)


def test_q16(env):
    ctx, paths, dfs = env
    s = streams(env)
    sizes = "(49, 14, 23, 45, 19, 3, 36, 9)"
    from quokka_tpu import col

    got = (
        s["partsupp"]
        .join(s["supplier"].filter(col("s_comment").str.contains("Customer Complaints")),
              left_on="ps_suppkey", right_on="s_suppkey", how="anti")
        .join(s["part"].filter_sql(
            f"p_brand != 'Brand#45' and not (p_type like 'MEDIUM POLISHED%') "
            f"and p_size in {sizes}"),
            left_on="ps_partkey", right_on="p_partkey")
        .groupby(["p_brand", "p_type", "p_size"])
        .agg_sql("count(distinct ps_suppkey) as supplier_cnt")
        .collect()
    )
    ps, su, pt = dfs["partsupp"], dfs["supplier"], dfs["part"]
    bad = su[su.s_comment.str.contains("Customer Complaints")].s_suppkey
    pf = pt[(pt.p_brand != "Brand#45")
            & ~pt.p_type.str.startswith("MEDIUM POLISHED")
            & pt.p_size.isin([49, 14, 23, 45, 19, 3, 36, 9])]
    j = ps[~ps.ps_suppkey.isin(bad)].merge(pf, left_on="ps_partkey", right_on="p_partkey")
    assert len(j) > 0
    exp = (
        j.groupby(["p_brand", "p_type", "p_size"])
        .ps_suppkey.nunique().reset_index(name="supplier_cnt")
    )
    sorted_eq(got, exp, by=["p_brand", "p_type", "p_size"])


def test_q17(env):
    ctx, paths, dfs = env
    s = streams(env)
    # the spec also filters p_container = 'MED BOX', but brand x container is
    # too selective for the mini dataset; the correlated avg rewrite is the
    # point of the query and is fully exercised by the brand filter alone
    li_part = s["lineitem"].join(
        s["part"].filter_sql("p_brand = 'Brand#23'"),
        left_on="l_partkey", right_on="p_partkey", how="semi",
    )
    avg_qty = li_part.groupby("l_partkey").agg_sql("avg(l_quantity) as avg_qty")
    got = (
        li_part
        .join(avg_qty.rename({"l_partkey": "a_partkey"}),
              left_on="l_partkey", right_on="a_partkey")
        .filter_sql("l_quantity < 0.2 * avg_qty")
        .agg_sql("sum(l_extendedprice) / 7.0 as avg_yearly")
        .collect()
    )
    l, pt = dfs["lineitem"], dfs["part"]
    pk = pt[pt.p_brand == "Brand#23"].p_partkey
    f = l[l.l_partkey.isin(pk)]
    assert len(f) > 0
    a = f.groupby("l_partkey").l_quantity.mean().rename("avg_qty")
    j = f.merge(a, on="l_partkey")
    sel = j[j.l_quantity < 0.2 * j.avg_qty]
    exp = sel.l_extendedprice.sum() / 7.0
    np.testing.assert_allclose(got.avg_yearly[0], exp, rtol=1e-9)


def test_q18(env):
    ctx, paths, dfs = env
    s = streams(env)
    big = (
        s["lineitem"].groupby("l_orderkey")
        .agg_sql("sum(l_quantity) as sum_qty")
        .filter_sql("sum_qty > 250")
    )
    got = (
        s["orders"]
        .join(big.rename({"l_orderkey": "b_orderkey"}),
              left_on="o_orderkey", right_on="b_orderkey")
        .join(s["customer"], left_on="o_custkey", right_on="c_custkey")
        .select(["c_name", "o_orderkey", "o_orderdate", "o_totalprice", "sum_qty"])
        .collect()
    )
    l, o, c = dfs["lineitem"], dfs["orders"], dfs["customer"]
    g = l.groupby("l_orderkey").l_quantity.sum()
    keys = g[g > 250]
    assert len(keys) > 0  # threshold tuned to the mini dataset
    exp = (
        o[o.o_orderkey.isin(keys.index)]
        .merge(keys.reset_index(name="sum_qty"), left_on="o_orderkey", right_on="l_orderkey")
        .merge(c, left_on="o_custkey", right_on="c_custkey")
    )[["c_name", "o_orderkey", "o_orderdate", "o_totalprice", "sum_qty"]]
    sorted_eq(got, exp, by=["o_orderkey"])


def test_q20(env):
    ctx, paths, dfs = env
    s = streams(env)
    forest_parts = s["part"].filter_sql("p_name like 'forest%'")
    shipped = (
        s["lineitem"]
        .filter_sql("l_shipdate >= date '1994-01-01' and "
                    "l_shipdate < date '1994-01-01' + interval '1' year")
        .groupby(["l_partkey", "l_suppkey"])
        .agg_sql("sum(l_quantity) as qty")
    )
    excess = (
        s["partsupp"]
        .join(forest_parts, left_on="ps_partkey", right_on="p_partkey", how="semi")
        .join(shipped, left_on=["ps_partkey", "ps_suppkey"],
              right_on=["l_partkey", "l_suppkey"])
        .filter_sql("ps_availqty > 0.5 * qty")
    )
    got = (
        s["supplier"]
        .join(s["nation"].filter_sql("n_name = 'CANADA'"),
              left_on="s_nationkey", right_on="n_nationkey", how="semi")
        .join(excess, left_on="s_suppkey", right_on="ps_suppkey", how="semi")
        .select(["s_name", "s_address"])
        .collect()
    )
    pt, l, ps, su, n = (dfs[k] for k in ("part", "lineitem", "partsupp", "supplier", "nation"))
    fp = pt[pt.p_name.str.startswith("forest")].p_partkey
    f = l[(l.l_shipdate >= datetime.date(1994, 1, 1)) & (l.l_shipdate < datetime.date(1995, 1, 1))]
    sq = f.groupby(["l_partkey", "l_suppkey"]).l_quantity.sum().reset_index(name="qty")
    ex = ps[ps.ps_partkey.isin(fp)].merge(
        sq, left_on=["ps_partkey", "ps_suppkey"], right_on=["l_partkey", "l_suppkey"]
    )
    ex = ex[ex.ps_availqty > 0.5 * ex.qty]
    ca = n[n.n_name == "CANADA"].n_nationkey
    exp = su[su.s_nationkey.isin(ca) & su.s_suppkey.isin(ex.ps_suppkey)][["s_name", "s_address"]]
    sorted_eq(got, exp, by=["s_name"])


def test_q21(env):
    ctx, paths, dfs = env
    s = streams(env)
    # spec names SAUDI ARABIA; the mini dataset's 30 suppliers may not cover
    # every nation, so use the modal supplier nation (same value both sides)
    _su, _n = dfs["supplier"], dfs["nation"]
    nat_key = int(_su.s_nationkey.mode()[0])
    nat_name = _n[_n.n_nationkey == nat_key].n_name.iloc[0]
    late = s["lineitem"].filter_sql("l_receiptdate > l_commitdate")
    n_supp = (
        s["lineitem"].select(["l_orderkey", "l_suppkey"]).distinct()
        .groupby("l_orderkey").agg_sql("count(*) as n_supp")
        .rename({"l_orderkey": "ns_orderkey"})
    )
    n_late = (
        late.select(["l_orderkey", "l_suppkey"]).distinct()
        .groupby("l_orderkey").agg_sql("count(*) as n_late")
        .rename({"l_orderkey": "nl_orderkey"})
    )
    got = (
        late.select(["l_orderkey", "l_suppkey"]).distinct()
        .join(s["orders"].filter_sql("o_orderstatus = 'F'"),
              left_on="l_orderkey", right_on="o_orderkey", how="semi")
        .join(n_supp, left_on="l_orderkey", right_on="ns_orderkey")
        .join(n_late, left_on="l_orderkey", right_on="nl_orderkey")
        .filter_sql("n_supp > 1 and n_late = 1")
        .join(s["supplier"], left_on="l_suppkey", right_on="s_suppkey")
        .join(s["nation"].filter_sql(f"n_name = '{nat_name}'"),
              left_on="s_nationkey", right_on="n_nationkey", how="semi")
        .groupby("s_name")
        .agg_sql("count(*) as numwait")
        .collect()
    )
    l, o, su, n = dfs["lineitem"], dfs["orders"], dfs["supplier"], dfs["nation"]
    pairs = l[["l_orderkey", "l_suppkey"]].drop_duplicates()
    ns = pairs.groupby("l_orderkey").size().rename("n_supp")
    lf = l[l.l_receiptdate > l.l_commitdate]
    lpairs = lf[["l_orderkey", "l_suppkey"]].drop_duplicates()
    nl = lpairs.groupby("l_orderkey").size().rename("n_late")
    fkeys = set(o[o.o_orderstatus == "F"].o_orderkey)
    j = lpairs.merge(ns, on="l_orderkey").merge(nl, on="l_orderkey")
    j = j[j.l_orderkey.isin(fkeys) & (j.n_supp > 1) & (j.n_late == 1)]
    sa = set(su[su.s_nationkey == nat_key].s_suppkey)
    j = j[j.l_suppkey.isin(sa)]
    assert len(j) > 0
    exp = (
        j.merge(su, left_on="l_suppkey", right_on="s_suppkey")
        .groupby("s_name").size().reset_index(name="numwait")
    )
    sorted_eq(got, exp, by=["s_name"])


def test_q22(env):
    ctx, paths, dfs = env
    s = streams(env)
    codes = ("13", "31", "23", "29", "30", "18", "17")
    in_list = ", ".join(f"'{c}'" for c in codes)
    cust = s["customer"].with_columns_sql(
        "substring(c_phone, 1, 2) as cntrycode"
    ).filter_sql(f"cntrycode in ({in_list})")
    avg_bal = float(
        cust.filter_sql("c_acctbal > 0.0")
        .agg_sql("avg(c_acctbal) as a").collect().a[0]
    )
    got = (
        cust.filter_sql(f"c_acctbal > {avg_bal}")
        .join(s["orders"], left_on="c_custkey", right_on="o_custkey", how="anti")
        .groupby("cntrycode")
        .agg_sql("count(*) as numcust, sum(c_acctbal) as totacctbal")
        .collect()
    )
    c, o = dfs["customer"], dfs["orders"]
    cc = c.assign(cntrycode=c.c_phone.str[:2])
    cf = cc[cc.cntrycode.isin(codes)]
    avg_e = cf[cf.c_acctbal > 0].c_acctbal.mean()
    sel = cf[(cf.c_acctbal > avg_e) & ~cf.c_custkey.isin(o.o_custkey)]
    assert len(sel) > 0
    exp = sel.groupby("cntrycode").agg(
        numcust=("c_custkey", "size"), totacctbal=("c_acctbal", "sum")
    ).reset_index()
    sorted_eq(got, exp, by=["cntrycode"])


class TestSkewedAndNullData:
    """VERDICT r1 item 4: distribution-sensitive data — Zipf-hot keys make
    giant groups/join fanouts, and nulls flow through real query shapes."""

    @pytest.fixture(scope="class")
    def skew_env(self, tmp_path_factory):
        root = tmp_path_factory.mktemp("tpch_skew")
        tables = tpch_data.generate(sf=0.003, seed=3, skew=True, nulls=True)
        paths = tpch_data.write_parquet_dir(tables, str(root))
        ctx = QuokkaContext(io_channels=2, exec_channels=2)
        dfs = {k: t.to_pandas() for k, t in tables.items()}
        return ctx, paths, dfs

    def test_q1_with_nulls(self, skew_env):
        ctx, paths, dfs = skew_env
        li = ctx.read_parquet(paths["lineitem"])
        got = (
            li.filter_sql("l_shipdate <= date '1998-09-02'")
            .groupby(["l_returnflag", "l_linestatus"])
            .agg_sql(
                "sum(l_quantity) as sum_qty, "
                "sum(l_extendedprice * (1 - l_discount)) as sum_disc_price, "
                "avg(l_discount) as avg_disc, count(l_tax) as n_tax, "
                "count(*) as n"
            )
            .collect()
        )
        l = dfs["lineitem"]
        f = l[l.l_shipdate <= datetime.date(1998, 9, 2)]
        exp = (
            f.groupby(["l_returnflag", "l_linestatus"], dropna=False)
            .apply(lambda d: pd.Series({
                "sum_qty": d.l_quantity.sum(),
                "sum_disc_price": (d.l_extendedprice * (1 - d.l_discount)).sum(),
                "avg_disc": d.l_discount.mean(),
                "n_tax": float(d.l_tax.notna().sum()),
                "n": float(len(d)),
            }), include_groups=False)
            .reset_index()
        )
        got = got.sort_values(["l_returnflag", "l_linestatus"], na_position="last").reset_index(drop=True)
        exp = exp.sort_values(["l_returnflag", "l_linestatus"], na_position="last").reset_index(drop=True)
        assert len(got) == len(exp)
        # null group present (nulls enabled at ~3%)
        assert got.l_returnflag.isna().any()
        np.testing.assert_allclose(got.sum_qty.to_numpy(), exp.sum_qty.to_numpy(), rtol=1e-9)
        np.testing.assert_allclose(got.sum_disc_price.to_numpy(), exp.sum_disc_price.to_numpy(), rtol=1e-9)
        np.testing.assert_allclose(got.avg_disc.to_numpy(), exp.avg_disc.to_numpy(), rtol=1e-9)
        np.testing.assert_array_equal(got.n_tax.to_numpy(dtype=float), exp.n_tax.to_numpy())

    def test_skewed_join_groupby(self, skew_env):
        ctx, paths, dfs = skew_env
        li = ctx.read_parquet(paths["lineitem"])
        pt = ctx.read_parquet(paths["part"])
        got = (
            li.join(pt, left_on="l_partkey", right_on="p_partkey")
            .groupby("p_brand")
            .agg_sql("sum(l_quantity) as q, count(*) as n")
            .collect()
        )
        l, p = dfs["lineitem"], dfs["part"]
        j = l.merge(p, left_on="l_partkey", right_on="p_partkey")
        # zipf skew: the hottest part should dominate
        top_share = l.l_partkey.value_counts().iloc[0] / len(l)
        assert top_share > 0.1
        exp = j.groupby("p_brand").agg(q=("l_quantity", "sum"), n=("l_quantity", "size")).reset_index()
        sorted_eq(got, exp, by=["p_brand"])
