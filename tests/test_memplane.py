"""Memory observability plane (obs/memplane.py): known-answer ledger
accounting, leak flagging with allocation-site attribution, the OOM
forensics bundle, and measured-admission precedence over size_hint()."""

import json
import os
import types

import pytest

from quokka_tpu.obs import memplane
from quokka_tpu.obs.memplane import (HOST, SITE_CKPT, SITE_READER,
                                     SITE_SHUFFLE, SITE_SPILL, MemLeakError,
                                     MemLedger)


class TestLedgerAccounting:
    def test_known_answer_totals(self):
        led = MemLedger()
        led.track(("a",), SITE_READER, 1000, query="q1")
        led.track(("b",), SITE_SHUFFLE, 500, query="q1")
        led.track(("c",), SITE_SPILL, 200, query="q2", device=HOST)
        assert led.live_bytes() == 1700
        assert led.live_bytes("q1") == 1500
        assert led.device_live_bytes() == 1500  # spill is host-class
        assert led.spill_bytes() == 200 == led.spill_bytes("q2")
        assert led.site_totals() == {"reader": 1000, "shuffle": 500,
                                     "spill": 200}
        assert led.entry_count() == 3 and led.entry_count("q1") == 2
        led.retire(("b",))
        assert led.live_bytes() == 1200
        assert led.peak_bytes() == 1700       # high-water mark holds
        assert led.peak_bytes("q1") == 1500
        # re-track of an existing token REPLACES (BatchCache dedup
        # semantics): never double-counts
        led.track(("a",), SITE_READER, 700, query="q1")
        assert led.live_bytes() == 900
        assert led.live_bytes("q1") == 700
        led.retire(("nope",))  # unknown token: no-op, no underflow
        assert led.live_bytes() == 900

    def test_retire_prefix_bulk_gc(self):
        led = MemLedger()
        led.track(("hbq", "/spill", "f1"), SITE_SPILL, 100, query="q",
                  device=HOST)
        led.track(("hbq", "/spill", "f2"), SITE_SPILL, 300, query="q",
                  device=HOST)
        led.track(("ckpt", "/spill", 0, 0, 1), SITE_CKPT, 50, query="q",
                  device=HOST)
        led.retire_prefix(("hbq", "/spill"))
        assert led.live_bytes() == 50
        assert led.spill_bytes("q") == 0
        assert led.entry_count() == 1
        fp = led.query_footprint("q")
        assert fp == {"live_bytes": 50, "peak_bytes": 450,
                      "spill_resident_bytes": 0}

    def test_reset_peak_rearms_at_live(self):
        led = MemLedger()
        led.track(("a",), SITE_READER, 1000)
        led.retire(("a",))
        led.track(("b",), SITE_READER, 10)
        assert led.peak_bytes() == 1000
        led.reset_peak()
        assert led.peak_bytes() == 10  # bench brackets each query with this

    def test_reconcile_delta_math(self, monkeypatch):
        led = MemLedger()
        vals = iter([1000, 1500])
        monkeypatch.setattr(memplane, "_jax_live_bytes", lambda: next(vals))
        led.set_baseline()  # jax=1000, ledger device-class = 0
        led.track(("a",), SITE_READER, 512)
        rec = led.reconcile(tolerance=0.10)
        assert rec["available"]
        assert rec["ledger_bytes"] == 512 and rec["jax_bytes"] == 500
        assert rec["within"] and rec["drift_frac"] <= 0.10

    def test_reconcile_unavailable_is_not_a_failure(self, monkeypatch):
        monkeypatch.setattr(memplane, "_jax_live_bytes", lambda: -1)
        led = MemLedger()
        led.set_baseline()
        rec = led.reconcile()
        assert rec["available"] is False and rec["within"] is True


class TestLeakFlagging:
    def test_leak_raises_with_site_attribution(self):
        from quokka_tpu import obs

        led = MemLedger()
        led.track(("cache", 1, "p0"), SITE_SHUFFLE, 4096, query="leaky")
        led.track(("scan", 2, "k"), SITE_READER, 100)  # query=None: exempt
        with pytest.raises(MemLeakError) as ei:
            led.check_leaks("leaky", strict=True)
        err = ei.value
        assert err.query_id == "leaky"
        assert [leak["site"] for leak in err.leaks] == ["shuffle"]
        assert err.leaks[0]["nbytes"] == 4096
        assert "leaky" in str(err) and "shuffle" in str(err)
        # the report RETIRES what it flags: no double-report, totals drop
        assert led.live_bytes() == 100
        assert led.check_leaks("leaky", strict=True) is None
        if obs.RECORDER.enabled:
            # allocation-site flight events attached, not just a byte count
            assert err.leaks[0]["events"], err.leaks[0]
            assert err.leaks[0]["events"][-1]["args"]["nbytes"] == 4096

    def test_clean_query_reports_none(self):
        led = MemLedger()
        led.track(("t",), SITE_READER, 10, query="q")
        led.retire(("t",))
        assert led.check_leaks("q", strict=True) is None

    def test_on_query_gc_reports_and_drops(self):
        led = MemLedger()
        led.track(("t",), SITE_READER, 10, query="q")
        err = led.on_query_gc("q")  # non-strict by default: report, no raise
        assert isinstance(err, MemLeakError)
        assert led.query_footprint("q") == {
            "live_bytes": 0, "peak_bytes": 0, "spill_resident_bytes": 0}

    def test_strict_mode_env(self, monkeypatch):
        monkeypatch.setenv("QK_MEM_STRICT", "1")
        led = MemLedger()
        led.track(("t",), SITE_READER, 10, query="q")
        with pytest.raises(MemLeakError):
            led.on_query_gc("q")


class TestOOMForensics:
    def test_bundle_contents(self, monkeypatch, tmp_path):
        monkeypatch.setenv("QK_DUMP_DIR", str(tmp_path))
        led = MemLedger()
        led.track(("big",), SITE_SHUFFLE, 1 << 20, query="q1")
        path = memplane.oom_bundle("test reason", ledger=led)
        assert path and os.path.exists(path)
        with open(path, encoding="utf-8") as f:
            bundle = json.load(f)
        assert bundle["reason"] == "test reason"
        assert bundle["live_bytes"] == 1 << 20
        assert bundle["top_holders"][0]["site"] == "shuffle"
        assert bundle["top_holders"][0]["nbytes"] == 1 << 20
        assert bundle["query_footprints"]["q1"]["peak_bytes"] == 1 << 20
        assert bundle["ledger_tail"][-1]["op"] == "track"
        assert "flight_timeline" in bundle
        assert bundle["site_bytes"]["shuffle"] == 1 << 20

    def test_budget_breach_latches_one_bundle_per_episode(
            self, monkeypatch, tmp_path):
        monkeypatch.setenv("QK_DUMP_DIR", str(tmp_path))
        monkeypatch.setenv("QK_MEM_BUDGET", "1000")
        led = MemLedger()
        led.track(("a",), SITE_READER, 600)
        assert not list(tmp_path.glob("mem-*.oom.json"))  # under budget
        led.track(("b",), SITE_READER, 600)  # 1200 > 1000: one bundle
        assert len(list(tmp_path.glob("mem-*.oom.json"))) == 1
        led.track(("c",), SITE_READER, 10)   # still breached: latched
        assert len(list(tmp_path.glob("mem-*.oom.json"))) == 1
        led.retire(("b",))
        led.retire(("c",))
        led.track(("d",), SITE_READER, 10)   # back under budget: re-arms
        led.track(("e",), SITE_READER, 600)  # breach #2: new bundle
        assert len(list(tmp_path.glob("mem-*.oom.json"))) == 2

    def test_alloc_guard_bundles_only_allocation_failures(
            self, monkeypatch, tmp_path):
        monkeypatch.setenv("QK_DUMP_DIR", str(tmp_path))
        with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
            with memplane.alloc_guard(SITE_READER):
                raise RuntimeError(
                    "RESOURCE_EXHAUSTED: while allocating 2.0G")
        assert len(list(tmp_path.glob("mem-*.oom.json"))) == 1
        with pytest.raises(ValueError, match="bad schema"):
            with memplane.alloc_guard(SITE_READER):
                raise ValueError("bad schema")  # not an allocator error
        assert len(list(tmp_path.glob("mem-*.oom.json"))) == 1


class TestMeasuredAdmission:
    def test_record_and_measure_roundtrip(self, monkeypatch, tmp_path):
        monkeypatch.setenv("QK_MEMPROFILE_DIR", str(tmp_path))
        memplane.record_footprint("plan-a", 123 << 20, 5 << 20)
        assert memplane.measured_footprint("plan-a") == 123 << 20
        # max-merge: a lightly-loaded later run never shrinks the figure
        memplane.record_footprint("plan-a", 50 << 20)
        assert memplane.measured_footprint("plan-a") == 123 << 20
        memplane.record_footprint("plan-a", 200 << 20)
        assert memplane.measured_footprint("plan-a") == 200 << 20
        assert memplane.measured_footprint("plan-b") is None
        assert memplane.measured_footprint(None) is None

    def test_estimate_prefers_measured_over_size_hint(
            self, monkeypatch, tmp_path):
        from quokka_tpu.service import admission

        monkeypatch.setenv("QK_MEMPROFILE_DIR", str(tmp_path))
        reader = types.SimpleNamespace(size_hint=lambda: 100 << 20)
        info = types.SimpleNamespace(kind="input", reader=reader)
        graph = types.SimpleNamespace(actors={0: info}, plan_fp="fp-1")
        est = admission.estimate_working_set(graph)
        assert est == int((100 << 20) * admission.PIPELINE_OVERHEAD)
        memplane.record_footprint("fp-1", 42 << 20)
        assert admission.estimate_working_set(graph) == 42 << 20
        # a measured figure is ground truth: a genuinely small plan is
        # admitted as small, NOT floored to MIN_ESTIMATE_BYTES
        graph2 = types.SimpleNamespace(actors={0: info}, plan_fp="fp-2")
        memplane.record_footprint("fp-2", 2 << 20)
        assert admission.estimate_working_set(graph2) == 2 << 20
        assert (admission.estimate_working_set(graph2)
                < admission.MIN_ESTIMATE_BYTES)

    def test_foreign_fingerprint_rejected_wholesale(
            self, monkeypatch, tmp_path):
        from quokka_tpu.service import admission

        monkeypatch.setenv("QK_MEMPROFILE_DIR", str(tmp_path))
        memplane.record_footprint("fp-x", 42 << 20)
        path = memplane._profile_path()
        with open(path, encoding="utf-8") as f:
            prof = json.load(f)
        prof["fingerprint"] = "someone-elses-backend"
        with open(path, "w", encoding="utf-8") as f:
            json.dump(prof, f)
        # footprints measured under a different device topology describe a
        # different placement: fall back to size_hint estimation
        assert memplane.measured_footprint("fp-x") is None
        graph = types.SimpleNamespace(actors={}, plan_fp="fp-x")
        assert (admission.estimate_working_set(graph)
                == admission.MIN_ESTIMATE_BYTES)

    def test_empty_profile_dir_disables(self, monkeypatch):
        monkeypatch.setenv("QK_MEMPROFILE_DIR", "")  # QK_STRATEGY_DIR idiom
        memplane.record_footprint("fp", 1 << 30)
        assert memplane.measured_footprint("fp") is None

    def test_corrupt_profile_is_absent_not_fatal(self, monkeypatch, tmp_path):
        monkeypatch.setenv("QK_MEMPROFILE_DIR", str(tmp_path))
        memplane.record_footprint("fp", 1 << 20)
        with open(memplane._profile_path(), "w", encoding="utf-8") as f:
            f.write("{not json")
        assert memplane.measured_footprint("fp") is None
        memplane.record_footprint("fp", 2 << 20)  # recovers by rewriting
        assert memplane.measured_footprint("fp") == 2 << 20
