"""qklint: each rule fires on its seeded fixture, the CLI gates on it, and
the private-API compat shim behaves (satellite: pinned-version test)."""

import os
import subprocess
import sys

import pytest

from quokka_tpu.analysis import compat
from quokka_tpu.analysis.lint import main as lint_main
from quokka_tpu.analysis.lint import run_lint

FIXTURES = os.path.join(os.path.dirname(__file__), "lint_fixtures")

CASES = [
    ("QK001", "qk001_module_jit.py", 3),     # call, partial, decorator
    ("QK002", "qk002_import_side_effect.py", 3),  # register, makedirs, Thread
    ("QK003", "qk003_private_api.py", 1),
    ("QK004", "qk004_host_sync.py", 3),      # asarray, branch, block_until_ready
    ("QK005", "qk005_unlocked.py", 2),       # dict store, list append
    ("QK006", "qk006_swallow.py", 1),
    ("QK007", "qk007_print.py", 1),          # library print; main() exempt
    ("QK008", "qk008_global_config.py", 3),  # jax.config, environ, module
    ("QK009", "qk009_io_timeout.py", 5),     # create_connection, settimeout(None), timeout=None, fsspec.open, fs.mv
    ("QK010", "qk010_counter_dict.py", 3),   # 2x dict +=, 1x .get()+1 RMW
    ("QK011", "qk011_push_sync.py", 3),      # np.asarray, .item(), device_get
    ("QK012", "qk012_raw_len_key.py", 3),    # sig tuple, .get key, store key
    ("QK013", "qk013_platform_gate.py", 3),  # probe, string gate, _platform
    ("QK018", "qk018_device_alloc.py", 3),   # jnp.zeros, device_put, asarray
    ("QK019", "qk019_row_tally.py", 3),      # attr +=, dict-slot +=, .get RMW
    ("QK020", "qk020_program_chain.py", 3),  # loop dispatch, straight #3, #4
    ("QK025", "qk025_lock_io.py", 3),        # open, sleep, helper->open
    ("QK027", "qk027_wall_timing.py", 3),    # dotted, name pair, bare
]


def _fixture(name):
    return os.path.join(FIXTURES, name)


@pytest.mark.parametrize("rule,fixture,expected", CASES,
                         ids=[c[0] for c in CASES])
def test_rule_fires_on_fixture(rule, fixture, expected):
    findings = run_lint([_fixture(fixture)])
    hits = [f for f in findings if f.rule == rule]
    assert len(hits) == expected, [f.render() for f in findings]
    # each fixture seeds exactly its own rule — cross-rule noise would make
    # fixtures useless as per-rule regression anchors
    assert {f.rule for f in findings} == {rule}, \
        [f.render() for f in findings]


@pytest.mark.parametrize("rule,fixture,expected", CASES,
                         ids=[c[0] for c in CASES])
def test_cli_exits_nonzero_on_fixture(rule, fixture, expected, capsys):
    rc = lint_main([_fixture(fixture), "--no-baseline", "--quiet"])
    assert rc == 1


def test_cli_subprocess_entry_point():
    """`python -m quokka_tpu.analysis.lint` works as a real process (the
    in-process tests above cover each rule; this covers the module entry)."""
    r = subprocess.run(
        [sys.executable, "-m", "quokka_tpu.analysis.lint",
         _fixture("qk006_swallow.py"), "--no-baseline"],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert r.returncode == 1, r.stdout + r.stderr
    assert "QK006" in r.stdout


def test_clean_code_produces_no_findings(tmp_path):
    p = tmp_path / "clean.py"
    p.write_text(
        "import threading\n"
        "import jax\n\n\n"
        "class Store:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.kv = {}\n\n"
        "    def put(self, k, v):\n"
        "        with self._lock:\n"
        "            self.kv[k] = v\n\n\n"
        "def make(f):\n"
        "    return jax.jit(f)\n"
    )
    assert run_lint([str(p)]) == []


def test_baseline_workflow(tmp_path):
    """New finding fails, baselined finding passes, baseline-only-shrinks:
    a fixed finding shows up as stale rather than silently lingering."""
    from quokka_tpu.analysis.lint import load_baseline, write_baseline

    fixture = _fixture("qk006_swallow.py")
    bl = tmp_path / "baseline.json"
    # no baseline: gate fails
    assert lint_main([fixture, "--baseline", str(bl), "--quiet"]) == 1
    # GROWING the baseline requires a real --reason (no TODO placeholder
    # auto-fill: every accepted finding ships with its rationale)
    assert lint_main([fixture, "--baseline", str(bl),
                      "--write-baseline"]) == 2
    assert lint_main([fixture, "--baseline", str(bl), "--write-baseline",
                      "--reason", "short"]) == 2          # < 10 chars
    assert lint_main([fixture, "--baseline", str(bl), "--write-baseline",
                      "--reason", "TODO: rationale"]) == 2  # placeholder
    assert lint_main([fixture, "--baseline", str(bl), "--write-baseline",
                      "--reason",
                      "fixture code swallows on purpose"]) == 0
    assert lint_main([fixture, "--baseline", str(bl), "--quiet"]) == 0
    from quokka_tpu.analysis.lint import load_baseline as _lb

    assert all(v == "fixture code swallows on purpose"
               for v in _lb(str(bl)).values())
    # SHRINK-only rewrites (no new entries) need no --reason
    assert lint_main([fixture, "--baseline", str(bl),
                      "--write-baseline"]) == 0
    # rationales survive a rewrite
    entries = load_baseline(str(bl))
    key = next(iter(entries))
    entries[key] = "accepted because reasons"
    write_baseline(str(bl), run_lint([fixture]), entries)
    assert load_baseline(str(bl))[key] == "accepted because reasons"
    # stale entries fail the gate too (baseline may only shrink, in the
    # same PR that fixes the finding) — same answer as test_lint_clean.py
    import json

    data = json.loads(bl.read_text())
    data["findings"]["QK999::gone/file.py::<module>::nothing"] = "fixed"
    bl.write_text(json.dumps(data))
    assert lint_main([fixture, "--baseline", str(bl), "--quiet"]) == 1


# -- satellite: version-guarded private-API shim ----------------------------


def test_compat_trace_state_clean_pinned_version():
    """The pinned jax must expose the API through the shim, and the shim
    must answer correctly in both dispatch contexts (the answer routes
    hashtable kernels around the nested-pjit dispatch race)."""
    import jax

    assert compat.trace_state_clean() is True
    seen = []

    def probe(x):
        seen.append(compat.trace_state_clean())
        return x

    jax.jit(probe)(1)
    assert seen == [False]


def test_compat_missing_api_fails_loudly():
    with pytest.raises(ImportError, match="trace_state_clean"):
        compat._resolve("trace_state_clean",
                        (("nonexistent_module", "nope"),
                         ("core", "definitely_not_there")))
