"""Compile plane: canonical signature ladder, AOT export/import round
trips, corrupt-artifact fallback, plan-ledger prewarm, and the signature
cardinality budget for a Q3-shaped plan."""

import functools
import json
import os
import threading

import jax.numpy as jnp
import numpy as np
import pyarrow as pa
import pytest

from quokka_tpu import config
from quokka_tpu.ops import sigkey
from quokka_tpu.runtime import compileplane


# ---------------------------------------------------------------------------
# signature ladder
# ---------------------------------------------------------------------------


def test_ladder_rungs_are_pow2_and_monotone():
    prev = 0
    for n in range(1, 200000, 997):
        b = sigkey.bucket_rows(n)
        assert b >= n
        assert b & (b - 1) == 0, f"bucket {b} not a power of two"
        assert b >= prev or n <= prev
        prev = b


def test_ladder_coarse_below_knee():
    # 4x rung spacing below the knee: 2048 and 4096 share the 4096 rung
    assert sigkey.bucket_rows(2048) == sigkey.bucket_rows(4096) == 4096
    assert sigkey.bucket_rows(8192) == 16384
    # above the knee the ladder is pure pow2 (padding waste is real there)
    assert sigkey.bucket_rows((1 << 16) + 1) == 1 << 17
    assert sigkey.bucket_rows((1 << 20) + 1) == 1 << 21


def test_ladder_bounds():
    assert sigkey.bucket_rows(0) == sigkey.MIN_BUCKET
    assert sigkey.bucket_rows(sigkey.MAX_BUCKET) == sigkey.MAX_BUCKET
    with pytest.raises(ValueError):
        sigkey.bucket_rows(sigkey.MAX_BUCKET + 1)


def test_config_bucket_size_delegates():
    assert config.bucket_size(3000) == sigkey.bucket_rows(3000)
    assert config.MIN_BUCKET == sigkey.MIN_BUCKET


def test_batch_sig_drops_kind_keeps_dtype():
    from quokka_tpu.ops.batch import NumCol

    d = NumCol(jnp.zeros(256, jnp.int32), "d")
    i = NumCol(jnp.zeros(256, jnp.int32), "i")
    # a date and an int column of the same device dtype trace to the same
    # program (kinds re-derive from dtypes inside the trace): canonical
    # signatures must differ only by name
    assert sigkey.col_sig("a", d)[1:] == sigkey.col_sig("a", i)[1:]
    # dtype and wide-limb presence DO decide the program
    w = NumCol(jnp.zeros(256, jnp.int32), "i", hi=jnp.zeros(256, jnp.int32))
    assert sigkey.col_sig("a", i) != sigkey.col_sig("a", w)


def test_make_key_records_in_ledger():
    sigkey.reset_ledger()
    k1 = sigkey.make_key("t_kind", 256, "a")
    sigkey.make_key("t_kind", 256, "a")  # duplicate: one ledger entry
    sigkey.make_key("t_kind", 1024, "a")
    assert sigkey.ledger_counts()["t_kind"] == 2
    assert k1 in sigkey.ledger_keys("t_kind")


# ---------------------------------------------------------------------------
# AOT persistence round trip
# ---------------------------------------------------------------------------


@pytest.fixture
def aot_dir(tmp_path, monkeypatch):
    d = tmp_path / "aotcache"
    monkeypatch.setenv("QUOKKA_AOT_CACHE_DIR", str(d))
    monkeypatch.setenv("QUOKKA_AOT_CACHE", "1")
    yield d


# Unique per test run: the shared XLA test cache must MISS on these toy
# programs (an executable the XLA persistent cache loaded serializes with
# unresolved symbols; compileplane verify-before-write would then skip
# persistence and the AOT round-trip tests would have nothing to test).
_RUN_TOKEN = int.from_bytes(os.urandom(4), "little") % 100_000


def _toy_builder(salt=0):
    import jax

    k = _RUN_TOKEN + salt

    @jax.jit
    def f(x, y):
        return x * 2 + y + k, jnp.sum(x)

    return f


def test_aot_roundtrip_bit_exact(aot_dir):
    key = sigkey.make_key("t_roundtrip", _RUN_TOKEN, 1, ((8,), "float32"))
    args = (jnp.arange(8.0, dtype=jnp.float32),
            jnp.ones(8, dtype=jnp.float32))
    prog = compileplane.acquire(key, functools.partial(_toy_builder, 1), args)
    out1 = prog(*args)
    compileplane.drain_writes()
    files = [f for f in os.listdir(compileplane._aot_dir()) if
             f.endswith(".aot")]
    assert files, "executable was not persisted"

    # a fresh program store (restarted process) must answer from disk
    compileplane.PROGRAMS.pop(key, None)
    prog2 = compileplane.acquire(key, functools.partial(_toy_builder, 1), args)
    assert isinstance(prog2, compileplane.AotProgram)
    out2 = prog2(*args)
    for a, b in zip(out1, out2):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_corrupt_artifact_falls_back_to_fresh_compile(aot_dir):
    key = sigkey.make_key("t_corrupt", _RUN_TOKEN, 2, ((4,), "float32"))
    args = (jnp.arange(4.0, dtype=jnp.float32),
            jnp.ones(4, dtype=jnp.float32))
    prog = compileplane.acquire(key, functools.partial(_toy_builder, 2), args)
    expect = [np.asarray(x) for x in prog(*args)]
    compileplane.drain_writes()
    path = compileplane._entry_path(key)
    assert os.path.exists(path)
    # flip bytes mid-file: the checksummed frame must catch it
    with open(path, "r+b") as f:
        f.seek(os.path.getsize(path) // 2)
        f.write(b"\xff\xff\xff\xff")
    compileplane.PROGRAMS.pop(key, None)
    prog2 = compileplane.acquire(key, functools.partial(_toy_builder, 2), args)  # never raises
    got = [np.asarray(x) for x in prog2(*args)]
    for a, b in zip(expect, got):
        assert np.array_equal(a, b)
    # the bad file was quarantined (a HEALTHY artifact may legitimately be
    # re-persisted at the same path by the fresh compile's writer)
    assert os.path.exists(path + ".corrupt")


def test_truncated_artifact_falls_back(aot_dir):
    key = sigkey.make_key("t_trunc", _RUN_TOKEN, 3, ((4,), "float32"))
    args = (jnp.arange(4.0, dtype=jnp.float32),
            jnp.ones(4, dtype=jnp.float32))
    compileplane.acquire(key, functools.partial(_toy_builder, 3), args)
    compileplane.drain_writes()
    path = compileplane._entry_path(key)
    with open(path, "r+b") as f:
        f.truncate(10)
    compileplane.PROGRAMS.pop(key, None)
    prog = compileplane.acquire(key, functools.partial(_toy_builder, 3), args)
    out = prog(*args)
    assert np.asarray(out[1]) == np.asarray(args[0]).sum()


def test_aval_mismatch_falls_back_to_jit(aot_dir):
    key = sigkey.make_key("t_mismatch", _RUN_TOKEN, 4, ((8,), "float32"))
    args8 = (jnp.arange(8.0, dtype=jnp.float32),
             jnp.ones(8, dtype=jnp.float32))
    prog = compileplane.acquire(key, functools.partial(_toy_builder, 4), args8)
    assert isinstance(prog, compileplane.AotProgram)
    # same program object called at DIFFERENT shapes (defensive: a key
    # collision must degrade to the jit fallback, not error)
    args4 = (jnp.arange(4.0, dtype=jnp.float32),
             jnp.ones(4, dtype=jnp.float32))
    out = prog(*args4)
    assert np.asarray(out[1]) == 6.0


def test_aot_kernel_call_inside_trace_inlines(aot_dir):
    import jax

    @jax.jit
    def inner(x):
        return x + 1

    @jax.jit
    def outer(x):
        # a compiled executable cannot trace; the guard must route to the
        # plain jitted callable (which inlines)
        return compileplane.aot_kernel_call("t_traced", inner, (x,)) * 2

    out = outer(jnp.arange(4.0))
    assert np.array_equal(np.asarray(out), [2.0, 4.0, 6.0, 8.0])


def test_aot_kernel_call_with_trailing_static(aot_dir):
    import functools

    import jax

    @functools.partial(jax.jit, static_argnames=("k",))
    def topk(x, k):
        return x[:k] + _RUN_TOKEN

    x = jnp.arange(8.0)
    expect = np.asarray(x)[:3] + _RUN_TOKEN
    out = compileplane.aot_kernel_call("t_static", topk, (x,), (3,))
    assert np.array_equal(np.asarray(out), expect)
    compileplane.drain_writes()
    # restart: the persisted executable answers, statics baked in
    key = sigkey.make_key("t_static", sigkey.aval_sig((x,)), 3)
    compileplane.PROGRAMS.pop(key, None)
    compileplane._INSTALLED_HASHES.discard(compileplane.key_hash(key))
    out2 = compileplane.aot_kernel_call("t_static", topk, (x,), (3,))
    assert np.array_equal(np.asarray(out2), expect)
    assert isinstance(compileplane.PROGRAMS[key], compileplane.AotProgram)


# ---------------------------------------------------------------------------
# plan ledger + prewarm
# ---------------------------------------------------------------------------


def test_plan_ledger_roundtrip_and_prewarm(aot_dir):
    key = sigkey.make_key("t_prewarm", _RUN_TOKEN, 5, ((8,), "float32"))
    args = (jnp.arange(8.0, dtype=jnp.float32),
            jnp.ones(8, dtype=jnp.float32))
    fp = "test-plan-fp"
    with compileplane.query_scope(None, fp):
        prog = compileplane.acquire(key, functools.partial(_toy_builder, 5), args)
    expect = [np.asarray(x) for x in prog(*args)]
    compileplane.drain_writes()
    compileplane.flush_plan(fp)
    assert compileplane.key_hash(key) in compileplane.plan_sig_hashes(fp)

    # "restart": drop the in-memory program, prewarm reinstalls from disk
    compileplane.PROGRAMS.pop(key, None)
    compileplane._INSTALLED_HASHES.discard(compileplane.key_hash(key))
    t = compileplane.prewarm_plan(fp, wait=True)
    assert t is not None
    prog2 = compileplane.PROGRAMS[key]
    assert isinstance(prog2, compileplane.AotProgram)
    assert prog2.prewarmed
    got = [np.asarray(x) for x in prog2(*args)]
    for a, b in zip(expect, got):
        assert np.array_equal(a, b)


def test_flush_plan_merges_not_overwrites(aot_dir, monkeypatch):
    fp = "test-merge-fp"
    path = compileplane._plan_path(fp, create=True)
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"v": 1, "sigs": ["deadbeef"]}, f)
    with compileplane.query_scope(None, fp):
        compileplane.note_program(("t_merge", 1))
    compileplane.flush_plan(fp)
    sigs = compileplane.plan_sig_hashes(fp)
    assert "deadbeef" in sigs
    assert compileplane.key_hash(("t_merge", 1)) in sigs


def test_per_query_counters_through_scope(aot_dir):
    from quokka_tpu import obs

    counters = {ev: obs.REGISTRY.counter(f"compile.{ev}.test-q")
                for ev in ("cache_hit", "miss", "prewarm_hit")}
    key = sigkey.make_key("t_counters", _RUN_TOKEN, 6, ((8,), "float32"))
    args = (jnp.arange(8.0, dtype=jnp.float32),
            jnp.ones(8, dtype=jnp.float32))
    with compileplane.query_scope(counters, None):
        compileplane.acquire(key, functools.partial(_toy_builder, 6), args)
    assert counters["miss"].value == 1
    compileplane.drain_writes()
    compileplane.PROGRAMS.pop(key, None)
    with compileplane.query_scope(counters, None):
        compileplane.acquire(key, functools.partial(_toy_builder, 6), args)
    assert counters["cache_hit"].value == 1
    obs.REGISTRY.remove(*(c.name for c in counters.values()))


def test_backend_fingerprint_shape():
    fp = compileplane.backend_fingerprint()
    assert fp.count("-") >= 2
    # a different topology is a different namespace (directory), so a
    # foreign artifact can never be loaded
    assert compileplane.backend_fingerprint() == fp  # stable within process


# ---------------------------------------------------------------------------
# signature cardinality budget (Q3-shaped plan)
# ---------------------------------------------------------------------------

# Checked-in budget: distinct fused/kernel program keys a Q3-shaped
# join+join+groupby query may create.  BENCH_r05 measured 11-15 REAL
# compiles per join query from signature fragmentation; the canonical
# ladder + normalized column signatures hold the whole per-kind key space
# to this budget.  If this fails after a change, either the change leaks
# signature cardinality (fix it) or it legitimately adds a program kind
# (bump the budget in the same PR that argues why).
SIG_BUDGETS = {
    "partial_agg": 4,
    "partial_agg_small": 2,
    "predicate": 3,
    "pk_probe_sorted": 4,
    "ht_probe": 4,
    "gather": 24,
    "fused_concat": 10,
}


@pytest.mark.parametrize("unused", [0])
def test_q3_shaped_plan_signature_budget(tmp_path, unused):
    import pyarrow.parquet as pq

    from quokka_tpu import QuokkaContext
    from quokka_tpu.expression import col

    r = np.random.default_rng(7)
    n_fact, n_dim = 60_000, 5_000
    fact = pa.table({
        "fk": r.integers(0, n_dim, n_fact).astype(np.int64),
        "v": r.integers(0, 1000, n_fact).astype(np.int64),
        "flag": r.integers(0, 4, n_fact).astype(np.int64),
    })
    dim = pa.table({
        "pk": np.arange(n_dim, dtype=np.int64),
        "grp": r.integers(0, 64, n_dim).astype(np.int64),
    })
    fp_, dp_ = str(tmp_path / "fact.parquet"), str(tmp_path / "dim.parquet")
    pq.write_table(fact, fp_, row_group_size=1 << 14)
    pq.write_table(dim, dp_)

    sigkey.reset_ledger()
    ctx = QuokkaContext(io_channels=2, exec_channels=2)
    out = (
        ctx.read_parquet(fp_)
        .filter(col("flag") < 3)
        .join(ctx.read_parquet(dp_), left_on="fk", right_on="pk")
        .groupby("grp")
        .agg_sql("sum(v) as sv, count(*) as n")
        .collect()
    )
    assert len(out) > 0
    counts = sigkey.ledger_counts()
    over = {k: (n, SIG_BUDGETS[k]) for k, n in counts.items()
            if k in SIG_BUDGETS and n > SIG_BUDGETS[k]}
    assert not over, (
        f"signature cardinality over budget: {over} (all: {counts}) — "
        "a cache-key dimension fragmented; derive it through ops/sigkey"
    )
