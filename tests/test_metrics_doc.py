"""Doc-drift gate: every metric family the runtime can emit must be
documented in README.md's metric-families table.

Family names come from two places, both checked:

1. The naming tables in obs/export.py (_LABEL_FAMILIES, _EXACT_FAMILIES,
   the strategy two-label special case) — the curated families.
2. Every string-literal instrument registration in the source tree
   (``REGISTRY.counter("...")`` etc.), mapped through export._family —
   the fallback-named families.  F-string registrations are per-query /
   per-site twins of families already covered by (1).

Adding a metric without a README row fails here, in the same PR.
"""

from __future__ import annotations

import os
import re

from quokka_tpu.obs import export

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_PKG = os.path.join(_ROOT, "quokka_tpu")
_README = os.path.join(_ROOT, "README.md")

# REGISTRY.counter("a.b")-style literal registrations (f-strings excluded:
# their families are the labeled ones declared in _LABEL_FAMILIES)
_REG_RE = re.compile(r"\b(counter|gauge|histogram)\(\s*\"([a-z0-9_.]+)\"")


def _source_instruments():
    found = set()
    for dirpath, dirnames, filenames in os.walk(_PKG):
        dirnames[:] = [d for d in dirnames if not d.startswith("__")]
        for fn in filenames:
            if not fn.endswith(".py"):
                continue
            with open(os.path.join(dirpath, fn), encoding="utf-8") as f:
                for kind, name in _REG_RE.findall(f.read()):
                    found.add((kind, name))
    assert found, "instrument scan found nothing — regex or layout drift"
    return found


def _documented_families():
    with open(_README, encoding="utf-8") as f:
        text = f.read()
    fams = set(re.findall(r"`(quokka_[a-z0-9_]+)`", text))
    assert fams, "README has no quokka_* family names — table moved?"
    return fams


def _expected_families():
    expected = set()
    for kind, _prefix, fam, _key in export._LABEL_FAMILIES:
        expected.add(fam + ("_total" if kind == "counter" else ""))
    for (kind, _name), fam in export._EXACT_FAMILIES.items():
        expected.add(fam + ("_total" if kind == "counter" else ""))
    expected.add("quokka_kernel_strategy_used_total")
    for kind, name in _source_instruments():
        fam, _label = export._family(name, kind)
        expected.add(fam + ("_total" if kind == "counter" else ""))
    # exporter-level extra gauges (export.metrics_text extra_gauges)
    expected.add("quokka_obs_dropped_events")
    expected.add("quokka_uptime_seconds")
    return expected


def test_every_metric_family_is_documented():
    documented = _documented_families()
    missing = sorted(f for f in _expected_families() if f not in documented)
    assert not missing, (
        "metric families missing from README.md's metric-families table "
        f"(add a row per family): {missing}")


def test_documented_quokka_families_parse():
    """The table rows use real family names: each documented quokka_*
    string must be producible by the naming rules (sanity against typos
    going stale the other way is intentionally loose — README may
    document families only emitted under optional planes)."""
    for fam in _documented_families():
        assert re.fullmatch(r"quokka_[a-z0-9_]+", fam)
