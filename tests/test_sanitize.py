"""Runtime sanitizer (QK_SANITIZE=1): watchdog, lock-order recorder,
recompile sentinel — units plus the end-to-end deadlocked-two-worker
fixture, which must fail fast with a stack dump instead of wedging to the
coordinator's 600 s timeout."""

import io
import os
import subprocess
import sys
import threading
import time

import pytest

from quokka_tpu.analysis import sanitize


# -- watchdog ---------------------------------------------------------------


def test_watchdog_fires_after_deadline_with_stack_dump():
    shots = []
    stream = io.StringIO()
    wd = sanitize.Watchdog("t", deadline=0.3, _exit=shots.append,
                           stream=stream).start()
    try:
        deadline = time.time() + 10
        while not shots and time.time() < deadline:
            time.sleep(0.05)
    finally:
        wd.stop()
    assert shots == [sanitize.WATCHDOG_EXIT_CODE]
    out = stream.getvalue()
    assert "WATCHDOG" in out and "no progress" in out
    # faulthandler dump: at least this (beating-test) thread's frames
    assert "Current thread" in out or "Thread" in out
    assert "test_sanitize" in out


def test_watchdog_beats_keep_it_quiet():
    shots = []
    wd = sanitize.Watchdog("t", deadline=0.4, _exit=shots.append,
                           stream=io.StringIO()).start()
    try:
        for _ in range(10):
            wd.beat()
            time.sleep(0.1)
    finally:
        wd.stop()
    assert shots == []


def test_start_watchdog_disabled_returns_none(monkeypatch):
    monkeypatch.delenv("QK_SANITIZE", raising=False)
    assert sanitize.start_watchdog("x") is None


# -- lock-order recorder ----------------------------------------------------


def test_lock_order_inversion_detected(capsys):
    sanitize.reset_lock_order()
    a = sanitize.InstrumentedLock("lockA", threading.Lock())
    b = sanitize.InstrumentedLock("lockB", threading.Lock())
    with a:
        with b:
            pass
    assert sanitize.lock_inversions() == []
    with b:
        with a:
            pass
    assert sanitize.lock_inversions() == [("lockA", "lockB")]
    assert "LOCK-ORDER INVERSION" in capsys.readouterr().err
    sanitize.reset_lock_order()


def test_lock_order_rlock_reentry_is_not_an_edge():
    sanitize.reset_lock_order()
    a = sanitize.InstrumentedLock("re", threading.RLock())
    with a:
        with a:
            pass
    assert sanitize.lock_inversions() == []
    sanitize.reset_lock_order()


def test_maybe_instrument_passthrough_when_disabled(monkeypatch):
    monkeypatch.delenv("QK_SANITIZE", raising=False)
    lk = threading.Lock()
    assert sanitize.maybe_instrument("x", lk) is lk
    monkeypatch.setenv("QK_SANITIZE", "1")
    wrapped = sanitize.maybe_instrument("x", lk)
    assert isinstance(wrapped, sanitize.InstrumentedLock)
    sanitize.reset_lock_order()


# -- recompile sentinel -----------------------------------------------------


def test_recompile_sentinel_raises_on_real_compiles():
    before = {"backend_compiles": 10, "cache_hits": 4}
    after = {"backend_compiles": 13, "cache_hits": 5}
    assert sanitize.real_compiles_delta(before, after) == 2
    with pytest.raises(sanitize.RecompileError, match="2 real backend"):
        sanitize.check_no_recompiles(before, after, context="timed runs",
                                     force=True)


def test_recompile_sentinel_ignores_cache_hits():
    before = {"backend_compiles": 10, "cache_hits": 4}
    after = {"backend_compiles": 12, "cache_hits": 6}  # hits, not compiles
    assert sanitize.check_no_recompiles(before, after, force=True) == 0


def test_recompile_sentinel_inert_without_flag(monkeypatch):
    monkeypatch.delenv("QK_SANITIZE", raising=False)
    before = {"backend_compiles": 0, "cache_hits": 0}
    after = {"backend_compiles": 5, "cache_hits": 0}
    assert sanitize.check_no_recompiles(before, after) == 5  # reports, no raise


def test_recompile_guard_clean_body(monkeypatch):
    monkeypatch.setenv("QK_SANITIZE", "1")
    with sanitize.recompile_guard("noop"):
        pass  # no compiles between the two snapshots


# -- end-to-end: deadlocked two-worker fixture ------------------------------


def test_deadlocked_workers_fail_fast_with_stack_dump():
    """The acceptance criterion: with QK_SANITIZE=1 the deliberately-
    deadlocked two-worker run produces a full stack dump and a nonzero exit
    within the watchdog deadline — not a 600 s coordinator timeout."""
    script = os.path.join(os.path.dirname(__file__),
                          "sanitize_deadlock_case.py")
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "QK_SANITIZE": "1",
        "QK_SANITIZE_DEADLINE": "5",
    }
    t0 = time.time()
    r = subprocess.run([sys.executable, script], capture_output=True,
                       text=True, timeout=240, env=env)
    elapsed = time.time() - t0
    out = r.stdout + r.stderr
    assert r.returncode != 0, out
    assert "UNEXPECTED-COMPLETION" not in out, out
    # fail-fast: worker spawn + first batch + 5s deadline + detection, far
    # under the 600 s coordinator timeout the round-5 wedge burned
    assert elapsed < 180, f"took {elapsed:.0f}s — watchdog did not fire"
    # the watchdog banner and a python-level stack dump naming the deadlock
    assert "WATCHDOG" in out, out
    assert "deadlock watchdog" in out, out  # coordinator's RuntimeError
    assert "execute" in out, out  # DeadlockExecutor frame in the dump
