"""Query service: persistent multi-query engine — concurrent execution on
one shared worker pool + control store, byte-budgeted admission control,
fair scheduling, warm shared caches, and cross-query failure recovery.

Acceptance (ISSUE 3): two concurrent TPC-H queries on one shared pool match
serial results; the admission gate queues a query past the byte budget and
releases it when one finishes; a worker kill during 2-way concurrency
recovers both queries without cross-query replay leakage.
"""

import os
import time

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from quokka_tpu import QuokkaContext
from quokka_tpu.dataset.readers import InputArrowDataset
from quokka_tpu.runtime import scancache
from quokka_tpu.runtime.tables import ControlStore
from quokka_tpu.service import (
    AdmissionQueueFull,
    AdmissionTimeout,
    QueryService,
)

import tpch_data


@pytest.fixture(autouse=True)
def fresh_scan_cache():
    scancache.clear()
    yield
    scancache.clear()


@pytest.fixture(scope="module")
def tpch_paths(tmp_path_factory):
    root = tmp_path_factory.mktemp("svc_tpch")
    tables = tpch_data.generate(sf=0.003, seed=7)
    paths = {}
    for name in ("lineitem", "orders", "customer"):
        p = str(root / f"{name}.parquet")
        pq.write_table(tables[name], p, row_group_size=4096)
        paths[name] = p
    return paths


def q1_stream(ctx, paths):
    return (
        ctx.read_parquet(
            paths["lineitem"],
            columns=["l_returnflag", "l_linestatus", "l_quantity",
                     "l_extendedprice", "l_discount"],
        )
        .groupby(["l_returnflag", "l_linestatus"])
        .agg_sql(
            "sum(l_quantity) as sum_qty, "
            "sum(l_extendedprice * (1 - l_discount)) as sum_disc_price, "
            "count(*) as n"
        )
    )


def q3_stream(ctx, paths):
    lineitem = ctx.read_parquet(
        paths["lineitem"],
        columns=["l_orderkey", "l_extendedprice", "l_discount"])
    orders = ctx.read_parquet(
        paths["orders"], columns=["o_orderkey", "o_custkey"])
    customer = ctx.read_parquet(
        paths["customer"], columns=["c_custkey", "c_mktsegment"])
    from quokka_tpu.expression import col

    return (
        lineitem.join(orders, left_on="l_orderkey", right_on="o_orderkey")
        .join(customer.filter(col("c_mktsegment") == "BUILDING"),
              left_on="o_custkey", right_on="c_custkey")
        .groupby("l_orderkey")
        .agg_sql("sum(l_extendedprice * (1 - l_discount)) as revenue, "
                 "count(*) as n")
    )


def _sorted(df, by):
    return df.sort_values(by).reset_index(drop=True)


def _no_namespace_rows(store: ControlStore, query_id: str) -> bool:
    for t in store.tables.values():
        if isinstance(t, set):
            if any(isinstance(m, tuple) and len(m) == 2 and m[0] == query_id
                   for m in t):
                return False
        elif any(isinstance(k, tuple) and len(k) == 2 and k[0] == query_id
                 for k in t):
            return False
    return all(not (isinstance(k, tuple) and query_id in k)
               for k in store.kv)


class TestConcurrentExecution:
    def test_two_concurrent_tpch_queries_match_serial(self, tpch_paths):
        serial_q1 = _sorted(q1_stream(QuokkaContext(), tpch_paths).collect(),
                            ["l_returnflag", "l_linestatus"])
        serial_q3 = _sorted(q3_stream(QuokkaContext(), tpch_paths).collect(),
                            ["l_orderkey"])
        with QueryService(pool_size=2) as svc:
            h1 = svc.submit(q1_stream(QuokkaContext(), tpch_paths))
            h3 = svc.submit(q3_stream(QuokkaContext(), tpch_paths))
            got1 = _sorted(h1.to_df(timeout=300),
                           ["l_returnflag", "l_linestatus"])
            got3 = _sorted(h3.to_df(timeout=300), ["l_orderkey"])
            pd.testing.assert_frame_equal(got1, serial_q1, rtol=1e-9,
                                          check_dtype=False)
            pd.testing.assert_frame_equal(got3, serial_q3, rtol=1e-9,
                                          check_dtype=False)
            # exact-count columns are byte-identical regardless of interleave
            assert got1["n"].tolist() == serial_q1["n"].tolist()
            assert got3["n"].tolist() == serial_q3["n"].tolist()
            # finished queries' namespaces are GC'd from the shared store
            assert _no_namespace_rows(svc.store, h1.query_id)
            assert _no_namespace_rows(svc.store, h3.query_id)

    def test_many_queries_share_one_pool(self, tpch_paths):
        serial = _sorted(q1_stream(QuokkaContext(), tpch_paths).collect(),
                         ["l_returnflag", "l_linestatus"])
        with QueryService(pool_size=2) as svc:
            handles = [svc.submit(q1_stream(QuokkaContext(), tpch_paths))
                       for _ in range(4)]
            for h in handles:
                got = _sorted(h.to_df(timeout=300),
                              ["l_returnflag", "l_linestatus"])
                pd.testing.assert_frame_equal(got, serial, rtol=1e-9,
                                              check_dtype=False)
            # per-query flight-recorder/metrics tagging: every query reports
            # its own progress counters under its own namespace
            rows = [sum(v["rows"] for k, v in h.metrics().items()
                        if isinstance(k, tuple)) for h in handles]
            assert len({r for r in rows if r > 0}) <= 1 and rows[0] > 0

    def test_stats_expose_latency_quantiles(self, tpch_paths):
        """ISSUE 5 satellite: stats()/handles carry per-query p50/p95 task
        latency + queue wait from the typed histograms (not just
        status/bytes), and the snapshot survives the namespace GC."""
        with QueryService(pool_size=2) as svc:
            h = svc.submit(q1_stream(QuokkaContext(), tpch_paths))
            h.wait(300)
            lat = h.latency_stats()
            assert lat["count"] > 0
            assert lat["p50"] > 0 and lat["p95"] >= lat["p50"]
            st = svc.stats()
            assert st["workers_alive"] == 2
            assert st["queue_wait"]["count"] >= 1  # admission wait observed
        # the per-query histogram is GC'd with the query's namespace...
        from quokka_tpu import obs

        assert f"task.latency_s.{h.query_id}" not in obs.REGISTRY.histograms()
        # ...but the handle still answers from its finish-time snapshot
        assert h.latency_stats()["count"] == lat["count"]

    def test_scan_cache_warm_across_queries(self, tpch_paths):
        with QueryService(pool_size=2) as svc:
            h1 = svc.submit(q1_stream(QuokkaContext(), tpch_paths))
            h1.wait(300)
            h2 = svc.submit(q1_stream(QuokkaContext(), tpch_paths))
            h2.wait(300)
            s1, s2 = h1.scan_cache_stats(), h2.scan_cache_stats()
            assert s1["misses"] > 0  # cold: first scan pays decode + h2d
            assert s2["hits"] > 0 and s2["misses"] == 0, (s1, s2)


class _SlowArrowDataset(InputArrowDataset):
    """Arrow reader with a per-lineage delay — deterministic 'long-running
    query' for admission-gate tests."""

    def __init__(self, table, batch_rows=512, delay_s=0.05):
        super().__init__(table, batch_rows=batch_rows)
        self.delay_s = delay_s

    def execute(self, channel, lineage):
        time.sleep(self.delay_s)
        return super().execute(channel, lineage)


def _slow_query(ctx, table, delay_s=0.05):
    return (
        ctx.read_dataset(_SlowArrowDataset(table, delay_s=delay_s))
        .groupby("k").agg_sql("sum(v) as sv, count(*) as n")
    )


def _small_table(n=8192, seed=0):
    r = np.random.default_rng(seed)
    return pa.table({"k": r.integers(0, 16, n).astype(np.int64),
                     "v": r.integers(0, 1000, n).astype(np.int64)})


class TestAdmissionControl:
    def test_gate_queues_third_query_and_releases(self):
        table = _small_table()
        want = (table.to_pandas().groupby("k")
                .agg(sv=("v", "sum"), n=("v", "count")).reset_index())
        mb = 1 << 20
        with QueryService(pool_size=2, mem_budget=100 * mb,
                          admit_timeout=120) as svc:
            hs = [svc.submit(_slow_query(QuokkaContext(), table),
                             working_set_bytes=40 * mb) for _ in range(3)]
            # two fit under the budget (80 MiB); the third must QUEUE
            deadline = time.time() + 30
            while time.time() < deadline:
                st = svc.stats()["admission"]
                if len(st["admitted"]) == 2 and len(st["waiting"]) == 1:
                    break
                time.sleep(0.01)
            st = svc.stats()["admission"]
            assert len(st["admitted"]) == 2 and len(st["waiting"]) == 1, st
            assert st["waiting"][0][0] == hs[2].query_id
            assert hs[2].status == "queued"
            # a finishing query returns budget and releases the waiter
            for h in hs:
                got = _sorted(h.to_df(timeout=300), ["k"])
                pd.testing.assert_frame_equal(got, want, check_dtype=False)
            assert svc.stats()["admission"]["used_bytes"] == 0

    def test_admission_timeout_is_named(self):
        table = _small_table()
        mb = 1 << 20
        with QueryService(pool_size=1, mem_budget=50 * mb,
                          admit_timeout=0.3) as svc:
            h1 = svc.submit(_slow_query(QuokkaContext(), table,
                                        delay_s=0.15),
                            working_set_bytes=40 * mb)
            h2 = svc.submit(_slow_query(QuokkaContext(), table),
                            working_set_bytes=40 * mb)
            with pytest.raises(AdmissionTimeout):
                h2.result(timeout=60)
            assert h1.to_df(timeout=300) is not None

    def test_bounded_queue_rejects_at_submit(self):
        table = _small_table()
        mb = 1 << 20
        with QueryService(pool_size=1, mem_budget=50 * mb, queue_depth=1,
                          admit_timeout=60) as svc:
            h1 = svc.submit(_slow_query(QuokkaContext(), table,
                                        delay_s=0.1),
                            working_set_bytes=40 * mb)
            h2 = svc.submit(_slow_query(QuokkaContext(), table),
                            working_set_bytes=40 * mb)  # waits (1 queued)
            with pytest.raises(AdmissionQueueFull):
                svc.submit(_slow_query(QuokkaContext(), table),
                           working_set_bytes=40 * mb)
            assert h1.to_df(timeout=300) is not None
            assert h2.to_df(timeout=300) is not None


class TestFaultRecovery:
    def test_worker_kill_recovers_both_queries(self, tmp_path):
        """Fault injection (the test_fault_tolerance.py hooks) fires inside
        BOTH queries while they share the pool; each recovers from its own
        namespaced checkpoint + spill WITHOUT replaying the neighbor's
        objects — byte-identical counts and matching sums prove no
        cross-query replay leakage."""
        r = np.random.default_rng(3)
        table = pa.table({
            "k": r.integers(0, 50, 20_000).astype(np.int64),
            "v": r.normal(size=20_000),
        })

        def q(ctx):
            return (ctx.read_dataset(InputArrowDataset(table,
                                                       batch_rows=1024))
                    .groupby("k").agg_sql("sum(v) as sv, count(*) as n"))

        serial = _sorted(q(QuokkaContext()).collect(), ["k"])
        cfg = dict(fault_tolerance=True, hbq_path=str(tmp_path),
                   checkpoint_interval=3,
                   inject_failure={"after_tasks": 12, "channels": [(1, 0)]})
        with QueryService(pool_size=2) as svc:
            ctxs = [QuokkaContext(), QuokkaContext()]
            for c in ctxs:
                for k, v in cfg.items():
                    c.set_config(k, v)
            handles = [svc.submit(q(c)) for c in ctxs]
            for h in handles:
                got = _sorted(h.to_df(timeout=300), ["k"])
                pd.testing.assert_frame_equal(got, serial, rtol=1e-9,
                                              check_dtype=False)
                assert got["n"].tolist() == serial["n"].tolist()
            # both injections actually fired, and both namespaces are GC'd
            # (spill files included — no leaked cross-query replay source)
            for h in handles:
                assert _no_namespace_rows(svc.store, h.query_id)
            leftover = [f for f in os.listdir(svc._spill_dir)
                        if f.startswith("hbq-")]
            assert not leftover, leftover


class TestExecConfigMerge:
    def test_service_level_config_survives_default_context(self):
        """A plain QuokkaContext carries the FULL default exec_config; its
        defaults must not silently revert service-level overrides."""
        t = _small_table(1024)
        with QueryService(pool_size=1,
                          exec_config={"max_pipeline": 9}) as svc:
            ctx = QuokkaContext()  # all defaults
            ctx.set_config("max_pipeline_batches", 11)  # explicit non-default
            h = svc.submit(ctx.from_arrow(t).groupby("k")
                           .agg_sql("sum(v) as sv"))
            cfg = h._s.graph.exec_config
            assert cfg["max_pipeline"] == 9       # service override kept
            assert cfg["max_pipeline_batches"] == 11  # ctx non-default wins
            assert h.to_df(timeout=300) is not None


class TestNamespacedStore:
    def test_two_namespaces_do_not_collide(self):
        root = ControlStore()
        a, b = root.namespace("qa"), root.namespace("qb")
        a.tset("LIT", (0, 0), 5)
        b.tset("LIT", (0, 0), 9)
        a.sadd("DST", (0, 0), "done")
        a.sadd("SAT", 3)
        b.sadd("SAT", 4)
        a.tape_append(0, 0, ("exec", 1, [], True))
        assert a.tget("LIT", (0, 0)) == 5
        assert b.tget("LIT", (0, 0)) == 9
        assert a.scontains("DST", (0, 0), "done")
        assert not b.scontains("DST", (0, 0), "done")
        assert a.smembers("SAT") == {3} and b.smembers("SAT") == {4}
        assert a.tape_len(0, 0) == 1 and b.tape_len(0, 0) == 0
        from quokka_tpu.runtime.task import ExecutorTask

        a.ntt_push(2, ExecutorTask(2, 0, 0, 0, {}))
        assert a.ntt_total() == 1 and b.ntt_total() == 0
        dropped = root.drop_namespace("qa")
        assert dropped > 0
        assert a.tget("LIT", (0, 0)) is None
        assert b.tget("LIT", (0, 0)) == 9  # the neighbor is untouched
        assert b.smembers("SAT") == {4}

    def test_one_shot_path_drops_its_namespace(self):
        ctx = QuokkaContext()
        t = _small_table(1024)
        df = ctx.from_arrow(t).groupby("k").agg_sql("sum(v) as sv").collect()
        assert len(df) > 0
        g = ctx.latest_graph
        assert g.query_id is not None
        assert _no_namespace_rows(g.root_store, g.query_id)
        assert g.metrics(), "metrics must survive the namespace GC"


@pytest.mark.skipif((os.cpu_count() or 1) < 4,
                    reason="concurrent speedup needs cores; the scheduling "
                           "overhead check below still runs everywhere")
def test_two_way_beats_serial_back_to_back(tpch_paths):
    # warm everything (compiles + scan cache)
    q1_stream(QuokkaContext(), tpch_paths).collect()
    q3_stream(QuokkaContext(), tpch_paths).collect()
    t0 = time.time()
    q1_stream(QuokkaContext(), tpch_paths).collect()
    q3_stream(QuokkaContext(), tpch_paths).collect()
    serial = time.time() - t0
    with QueryService(pool_size=2) as svc:
        t0 = time.time()
        h1 = svc.submit(q1_stream(QuokkaContext(), tpch_paths))
        h2 = svc.submit(q3_stream(QuokkaContext(), tpch_paths))
        h1.wait(300)
        h2.wait(300)
        wall = time.time() - t0
    assert wall < serial, (wall, serial)
