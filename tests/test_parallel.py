"""Multi-chip parallel plane tests on the virtual 8-device mesh: collective
hash shuffle conservation, distributed group-by, shuffle-join, and the driver
entry points."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from quokka_tpu.parallel.mesh import (
    distributed_groupby_step,
    distributed_join_groupby_step,
    make_mesh,
)


@pytest.fixture(scope="module")
def mesh():
    assert jax.device_count() == 8
    return make_mesh(8)


class TestDistributedGroupby:
    def test_conserves_rows_and_sums(self, mesh):
        per_dev, n_dev = 256, 8
        total = per_dev * n_dev
        r = np.random.default_rng(0)
        keys = r.integers(0, 37, total).astype(np.int32)
        vals = r.normal(size=total).astype(np.float32)
        valid = np.ones(total, dtype=bool)
        step = distributed_groupby_step(mesh, key_cols=1, val_ops=("sum", "count"))
        fkeys, fsum, fcnt, fvalid = step(keys, vals, vals, valid)
        assert int(jnp.sum(jnp.where(fvalid, fcnt, 0))) == total
        np.testing.assert_allclose(
            float(jnp.sum(jnp.where(fvalid, fsum, 0.0))), vals.sum(), rtol=1e-4
        )
        # per-key totals match numpy
        got = {}
        ks, ss, vs = np.asarray(fkeys), np.asarray(fsum), np.asarray(fvalid)
        for k, s, v in zip(ks, ss, vs):
            if v:
                assert k not in got, "key appears on two devices after shuffle"
                got[k] = s
        for k in range(37):
            np.testing.assert_allclose(got[k], vals[keys == k].sum(), rtol=1e-4)

    def test_invalid_rows_dropped(self, mesh):
        total = 8 * 128
        keys = np.zeros(total, dtype=np.int32)
        vals = np.ones(total, dtype=np.float32)
        valid = np.zeros(total, dtype=bool)
        valid[: total // 2] = True
        step = distributed_groupby_step(mesh, key_cols=1, val_ops=("count",))
        fkeys, fcnt, fvalid = step(keys, vals, valid)
        assert int(jnp.sum(jnp.where(fvalid, fcnt, 0))) == total // 2


class TestDistributedJoin:
    def test_shuffle_join_psum(self, mesh):
        total = 8 * 256
        r = np.random.default_rng(1)
        l_key = r.integers(0, 100, total).astype(np.int32)
        l_val = r.normal(size=total).astype(np.float32)
        r_key = np.arange(100, dtype=np.int32)
        r_val = r.normal(size=100).astype(np.float32)
        pad = total - 100
        r_key = np.concatenate([r_key, np.zeros(pad, np.int32)])
        r_val = np.concatenate([r_val, np.zeros(pad, np.float32)])
        r_valid = np.concatenate([np.ones(100, bool), np.zeros(pad, bool)])
        step = distributed_join_groupby_step(mesh)
        tot, rows = step(l_key, l_val, np.ones(total, bool), r_key, r_val, r_valid)
        assert int(rows) == total
        expect = float((l_val * r_val[np.clip(l_key, 0, 99)]).sum())
        np.testing.assert_allclose(float(tot), expect, rtol=1e-3)


class TestGraftEntry:
    def test_entry_compiles_and_runs(self):
        import sys, os

        sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        import __graft_entry__ as ge

        fn, args = ge.entry()
        out = jax.jit(fn)(*args)
        qty = np.asarray(out[0])
        count = np.asarray(out[-1])
        assert count.sum() > 0 and np.isfinite(qty).all()

    def test_dryrun_multichip(self):
        import sys, os

        sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        import __graft_entry__ as ge

        ge.dryrun_multichip(8)
