"""Test harness: run everything on a virtual 8-device CPU mesh with x64 so
results compare exactly against the pandas oracle.  Must set env before jax
initializes (hence top-of-module, before any quokka_tpu import)."""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
# Persistent compile cache across test runs: CPU compiles are cheap singly but
# the suite compiles thousands of programs; warm runs skip nearly all of it.
os.environ.setdefault(
    "QUOKKA_JAX_CACHE_DIR", os.path.expanduser("~/.cache/quokka_tpu_test_jax")
)
os.environ.setdefault("QUOKKA_JAX_CACHE_MIN_SECS", "0")
# Bound the distributed coordinator's run timeout for the whole suite: the
# default 600s means one wedged kill-recovery race (a known, pre-existing
# flake in the adopter's lost-object wait — see ROADMAP) eats the entire
# tier-1 budget before failing.  120s is ~5x the slowest healthy
# distributed test on a loaded 1-core box; a genuine wedge now fails THAT
# test loudly (with its stall dump) instead of timing out the suite.
os.environ.setdefault("QK_COORD_TIMEOUT", "120")
# Kernel-strategy calibration must never leak into tests: a developer box
# whose bench calibrated (ops/strategy.py) would otherwise flip which
# kernels tests exercise.  "" disables profile load/persist; tests that
# exercise calibration point QK_STRATEGY_DIR at a tmp dir and reset().
os.environ.setdefault("QK_STRATEGY_DIR", "")
# Same discipline for the admission feedback profiles (obs/memplane.py
# measured footprints, obs/opstats.py measured cardinalities): a developer
# box with populated caches would flip est_bytes in admission tests.
os.environ.setdefault("QK_MEMPROFILE_DIR", "")
os.environ.setdefault("QK_CARDPROFILE_DIR", "")
# Same again for the device-profile plane (obs/devprof.py calibrated peaks
# + observed throughputs): a calibrated developer box would flip the cost
# model's seconds basis from hint to roofline under tests.  Tests that
# exercise calibration point QK_DEVPROF_DIR at a tmp dir and reset().
os.environ.setdefault("QK_DEVPROF_DIR", "")
# Plan-invariant verification (analysis/planck.py QK021-QK024) is default-ON
# for every test: each optimizer pass's (before, after) plan pair is checked
# and a violation fails the test naming the pass and offending node.
os.environ.setdefault("QK_PLAN_VERIFY", "1")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax

# The axon sitecustomize forces the TPU platform programmatically, overriding
# the env var — force CPU back before any backend initializes.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
# CPU compiles are individually fast (mostly < 0.5s, the production cache
# threshold) but number in the thousands across the suite: cache all of them.
try:
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
except Exception:
    pass

assert jax.default_backend() == "cpu", jax.devices()
assert jax.device_count() == 8, jax.devices()

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(42)


def make_table(n=1000, seed=0):
    """A mixed-type test table with strings, ints, floats, dates."""
    r = np.random.default_rng(seed)
    return pa.table(
        {
            "k": r.integers(0, 20, n).astype(np.int64),
            "v": r.normal(size=n),
            "q": r.integers(1, 50, n).astype(np.int64),
            "s": np.array([["apple", "banana", "cherry", "date"][i] for i in r.integers(0, 4, n)]),
            "d": pa.array(r.integers(8000, 12000, n).astype(np.int32), type=pa.int32()).cast(
                pa.date32()
            ),
        }
    )


@pytest.fixture
def table():
    return make_table()


@pytest.fixture
def pdf(table):
    return table.to_pandas()


# -- slow tier (SF>=1 correctness passes) -------------------------------------
# `pytest -m slow` runs them; default runs skip them so the suite stays fast.


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: SF>=1 correctness passes with production spill "
        "thresholds (run with -m slow)"
    )


def pytest_collection_modifyitems(config, items):
    if config.getoption("-m"):
        return  # an explicit marker expression decides what runs
    skip = pytest.mark.skip(reason="slow tier; run with `pytest -m slow`")
    for item in items:
        if "slow" in item.keywords:
            item.add_marker(skip)
