"""Test harness: run everything on a virtual 8-device CPU mesh with x64 so
results compare exactly against the pandas oracle.  Must set env before jax
initializes (hence top-of-module, before any quokka_tpu import)."""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["QUOKKA_JAX_CACHE_DIR"] = "0"  # persistent cache is for TPU runs only
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax

# The axon sitecustomize forces the TPU platform programmatically, overriding
# the env var — force CPU back before any backend initializes.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

assert jax.default_backend() == "cpu", jax.devices()
assert jax.device_count() == 8, jax.devices()

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(42)


def make_table(n=1000, seed=0):
    """A mixed-type test table with strings, ints, floats, dates."""
    r = np.random.default_rng(seed)
    return pa.table(
        {
            "k": r.integers(0, 20, n).astype(np.int64),
            "v": r.normal(size=n),
            "q": r.integers(1, 50, n).astype(np.int64),
            "s": np.array([["apple", "banana", "cherry", "date"][i] for i in r.integers(0, 4, n)]),
            "d": pa.array(r.integers(8000, 12000, n).astype(np.int32), type=pa.int32()).cast(
                pa.date32()
            ),
        }
    )


@pytest.fixture
def table():
    return make_table()


@pytest.fixture
def pdf(table):
    return table.to_pandas()
