"""Streaming plane: watermark semantics, tailing sources, standing queries.

Covers the contracts the smoke relies on, at unit granularity:
- WatermarkClock min-combine / stream-done / snapshot round trip;
- pane semantics under late, duplicate-delivery and out-of-order batches,
  and pane finalization ordering (each pane exactly once, window order);
- tailing reader: append-while-reading (partial trailing line untouched),
  truncation detected LOUDLY, frozen-lineage re-reads byte-identical;
- end-to-end standing queries through QueryService.submit_continuous:
  incremental deltas, stop()-drain bit-exact vs pandas, kill-mid-stream
  recovery, manifest resume across a service teardown.
"""

import math
import os
import threading
import time

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from quokka_tpu.ops import bridge
from quokka_tpu.streaming import (
    StreamTruncatedError,
    StreamingWindowAggExecutor,
    TailingCsvReader,
    WatermarkClock,
    tail_window_agg,
)

EV_SCHEMA = pa.schema([("t", pa.int64()), ("k", pa.int64()),
                       ("v", pa.float64())])


def _batch(rows, wm=None, ch=0):
    t = pa.table({"t": pa.array([r[0] for r in rows], pa.int64()),
                  "k": pa.array([r[1] for r in rows], pa.int64()),
                  "v": pa.array([float(r[2]) for r in rows], pa.float64())})
    b = bridge.arrow_to_device(t)
    if wm is not None:
        b._stream_wm = float(wm)
        b._stream_ch = ch
    return b


def _win_exec(size=10):
    ex = StreamingWindowAggExecutor(
        "t", ["k"], size, [("s", "sum", "v"), ("n", "count", None)])
    ex.bind_query(None)
    return ex


def _panes(out):
    if out is None:
        return []
    df = bridge.to_pandas(out)
    return [tuple(r) for r in
            df[["window_start", "k", "s", "n"]].itertuples(index=False)]


class TestWatermarkClock:
    def test_min_across_channels_and_streams(self):
        c = WatermarkClock({0: 2, 1: 1})
        assert c.current() == -math.inf
        c.observe(0, 0, 10.0)
        assert c.current() == -math.inf  # two channels still silent
        c.observe(0, 1, 7.0)
        c.observe(1, 0, 5.0)
        assert c.current() == 5.0
        c.observe(1, 0, 20.0)
        assert c.current() == 7.0

    def test_watermarks_never_regress(self):
        c = WatermarkClock({0: 1})
        c.observe(0, 0, 10.0)
        c.observe(0, 0, 3.0)  # a replayed/duplicate lower mark is a no-op
        assert c.current() == 10.0

    def test_stream_done_contributes_inf(self):
        c = WatermarkClock({0: 1, 1: 1})
        c.observe(0, 0, 4.0)
        c.stream_done(1)  # never spoke: complete anyway
        assert c.current() == 4.0
        c.stream_done(0)
        assert c.current() == math.inf

    def test_snapshot_roundtrip(self):
        c = WatermarkClock({0: 2})
        c.observe(0, 0, 9.0)
        c.stream_done(0)
        c2 = WatermarkClock({0: 2})
        c2.restore(c.snapshot())
        assert c2.current() == c.current() == math.inf


class TestWindowPaneSemantics:
    def test_incremental_finalization_in_window_order(self):
        ex = _win_exec(10)
        # batch 1: windows 0 and 1 open, wm 9 -> nothing closes (end 10 > 9)
        assert ex.execute([_batch([(1, 0, 2), (12, 0, 3)], wm=9)], 0, 0) is None
        # wm 20 closes window 0 AND window 1 ([10,20) end == 20 <= 20)
        got = _panes(ex.execute([_batch([(25, 0, 1)], wm=20)], 0, 0))
        assert got == [(0, 0, 2.0, 1), (10, 0, 3.0, 1)]
        # done(): flush the remaining open pane
        assert _panes(ex.done(0)) == [(20, 0, 1.0, 1)]
        assert ex.panes == {}

    def test_out_of_order_within_delay_is_not_late(self):
        ex = _win_exec(10)
        ex.execute([_batch([(15, 0, 5)], wm=8)], 0, 0)  # wm lags max t
        out = ex.execute([_batch([(9, 0, 7)], wm=9)], 0, 0)  # behind 15, fine
        assert out is None
        got = _panes(ex.execute([_batch([(40, 0, 1)], wm=30)], 0, 0))
        assert (0, 0, 7.0, 1) in got and (10, 0, 5.0, 1) in got
        assert ex.late_rows == 0

    def test_late_rows_dropped_and_counted(self):
        from quokka_tpu import obs

        before = obs.REGISTRY.counter("stream.late_dropped").value
        ex = _win_exec(10)
        ex.execute([_batch([(5, 0, 1)], wm=25)], 0, 0)  # closes w0, w1
        out = ex.execute([_batch([(3, 0, 99), (26, 0, 4)], wm=25)], 0, 0)
        assert ex.late_rows == 1  # t=3 belongs to the closed window 0
        assert obs.REGISTRY.counter("stream.late_dropped").value == before + 1
        assert out is None
        assert _panes(ex.done(0)) == [(20, 0, 4.0, 1)]

    def test_duplicate_batch_replay_is_deterministic(self):
        """Identical (state, batch sequence) -> identical emissions: the
        tape-replay determinism the engine asserts during recovery."""
        batches = [
            [_batch([(1, 0, 2), (4, 1, 3)], wm=4)],
            [_batch([(11, 0, 1)], wm=11)],
            [_batch([(25, 1, 6)], wm=22)],
        ]
        def run():
            ex = _win_exec(10)
            outs = [_panes(ex.execute(bs, 0, 0)) for bs in batches]
            outs.append(_panes(ex.done(0)))
            return outs
        assert run() == run()

    def test_two_aggs_over_one_column(self):
        # min+max over the same column: the per-batch selection must not
        # produce duplicate labels (a Series-valued partial poisons
        # finalization)
        ex = StreamingWindowAggExecutor(
            "t", ["k"], 10, [("lo", "min", "v"), ("hi", "max", "v"),
                             ("n", "count", None)])
        ex.bind_query(None)
        outs = [ex.execute([_batch([(1, 0, 5), (3, 0, 2), (14, 0, 9)],
                                   wm=14)], 0, 0), ex.done(0)]
        got = pd.concat([bridge.to_pandas(o) for o in outs if o is not None],
                        ignore_index=True)
        assert got[["lo", "hi", "n"]].values.tolist() == [[2.0, 5.0, 2],
                                                          [9.0, 9.0, 1]]

    def test_checkpoint_restore_continues_exactly(self):
        ex = _win_exec(10)
        ex.execute([_batch([(1, 0, 2), (12, 1, 3)], wm=11)], 0, 0)
        snap = ex.checkpoint()
        rest = StreamingWindowAggExecutor(
            "t", ["k"], 10, [("s", "sum", "v"), ("n", "count", None)])
        rest.bind_query(None)
        rest.restore(snap)
        a = _panes(ex.execute([_batch([(30, 0, 1)], wm=25)], 0, 0)) \
            + _panes(ex.done(0))
        b = _panes(rest.execute([_batch([(30, 0, 1)], wm=25)], 0, 0)) \
            + _panes(rest.done(0))
        assert a == b


class TestTailingCsvReader:
    def _write(self, path, text, mode="w"):
        with open(path, mode) as f:
            f.write(text)

    def test_append_while_reading(self, tmp_path):
        p = str(tmp_path / "e.csv")
        self._write(p, "1,0,2.0\n5,1,3.0\n")
        r = TailingCsvReader(p, EV_SCHEMA, "t")
        segs = r.poll(0)
        assert len(segs) == 1 and r.lineage_time_max(segs[0]) == 5.0
        assert r.poll(0) == []  # nothing new
        self._write(p, "9,0,4.0\n", mode="a")
        seg2 = r.poll(0)
        assert len(seg2) == 1
        t = r.execute(0, seg2[0])
        assert t.column("t").to_pylist() == [9]

    def test_partial_trailing_line_left_unread(self, tmp_path):
        p = str(tmp_path / "e.csv")
        self._write(p, "1,0,2.0\n5,1,")  # append race: no trailing newline
        r = TailingCsvReader(p, EV_SCHEMA, "t")
        segs = r.poll(0)
        assert len(segs) == 1
        assert r.execute(0, segs[0]).num_rows == 1  # only the complete row
        self._write(p, "3.0\n", mode="a")  # the line completes
        seg2 = r.poll(0)
        assert len(seg2) == 1
        assert r.execute(0, seg2[0]).column("t").to_pylist() == [5]

    def test_frozen_lineage_rereads_identically(self, tmp_path):
        p = str(tmp_path / "e.csv")
        self._write(p, "1,0,2.0\n5,1,3.0\n")
        r = TailingCsvReader(p, EV_SCHEMA, "t")
        seg = r.poll(0)[0]
        first = r.execute(0, seg)
        self._write(p, "9,9,9.0\n", mode="a")  # appends must not change it
        assert r.execute(0, seg).equals(first)

    def test_truncation_detected_loudly(self, tmp_path):
        p = str(tmp_path / "e.csv")
        self._write(p, "1,0,2.0\n5,1,3.0\n")
        r = TailingCsvReader(p, EV_SCHEMA, "t")
        seg = r.poll(0)[0]
        self._write(p, "1,0,2.0\n")  # file shrinks below emitted offset
        with pytest.raises(StreamTruncatedError):
            r.poll(0)
        with pytest.raises(StreamTruncatedError):
            r.execute(0, seg)

    def test_seed_resumes_discovery_past_log(self, tmp_path):
        p = str(tmp_path / "e.csv")
        self._write(p, "1,0,2.0\n5,1,3.0\n")
        r = TailingCsvReader(p, EV_SCHEMA, "t")
        log = r.poll(0)
        self._write(p, "9,0,4.0\n", mode="a")
        r2 = TailingCsvReader(p, EV_SCHEMA, "t")
        r2.seed(log)  # adopts the manifest's segmentation
        segs = r2.poll(0)
        assert len(segs) == 1
        assert r2.execute(0, segs[0]).column("t").to_pylist() == [9]


def _truth(df, size=100):
    d = df.copy()
    d["window_start"] = (d.t // size) * size
    out = (d.groupby(["window_start", "k"])
           .agg(s=("v", "sum"), n=("v", "count")).reset_index())
    return out.sort_values(["window_start", "k"]).reset_index(drop=True)


def _merge_deltas(tables):
    merged = {}
    for tb in tables:
        for r in tb.to_pylist():
            key = (r["window_start"], r["k"])
            val = (r["s"], r["n"])
            assert merged.get(key, val) == val, \
                f"pane {key} re-delivered with different content"
            merged[key] = val
    return pd.DataFrame(
        [(ws, k, s, n) for (ws, k), (s, n) in merged.items()],
        columns=["window_start", "k", "s", "n"],
    ).sort_values(["window_start", "k"]).reset_index(drop=True)


def _assert_exact(got, want):
    for c in want.columns:
        got[c] = got[c].astype(np.float64)
        want[c] = want[c].astype(np.float64)
    pd.testing.assert_frame_equal(got[want.columns.tolist()], want,
                                  check_exact=True)


class TestStandingQueryService:
    def _run(self, tmp_path, n=3000, inject=None, chaos=None):
        from quokka_tpu import QuokkaContext
        from quokka_tpu.chaos import publish_env
        from quokka_tpu.service import QueryService

        rng = np.random.default_rng(13)
        df = pd.DataFrame({
            "t": np.sort(rng.integers(0, 1000, n)),
            "k": rng.integers(0, 4, n),
            "v": rng.integers(0, 50, n).astype(np.float64),
        })
        rows = [f"{r.t},{r.k},{r.v}\n" for r in df.itertuples(index=False)]
        p = str(tmp_path / "events.csv")
        with open(p, "w") as f:
            f.writelines(rows[:400])

        def appender():
            i = 400
            while i < n:
                j = min(i + 300, n)
                with open(p, "a") as f:
                    f.writelines(rows[i:j])
                i = j
                time.sleep(0.04)

        th = threading.Thread(target=appender, daemon=True)
        ecfg = {"fault_tolerance": True, "checkpoint_interval": 3}
        if inject:
            ecfg["inject_failure"] = inject
        if chaos:
            publish_env(chaos)
        try:
            svc = QueryService(pool_size=2, spill_dir=str(tmp_path / "spill"),
                               exec_config=ecfg)
            ctx = QuokkaContext()
            ds = tail_window_agg(
                ctx, TailingCsvReader(p, EV_SCHEMA, "t"), size=100, by="k",
                aggs=[("s", "sum", "v"), ("n", "count", None)])
            h = svc.submit_continuous(ds)
            th.start()
            deltas, polls_with_data = [], 0
            th.join()
            t0 = time.time()
            while time.time() - t0 < 30:
                got = h.poll_deltas()
                if got:
                    polls_with_data += 1
                    deltas.extend(got)
                wm = h.watermark()
                if wm is not None and wm >= float(df.t.max()):
                    break
                time.sleep(0.05)
            h.stop(timeout=60)
            deltas.extend(h.poll_deltas())
            _assert_exact(_merge_deltas(deltas), _truth(df))
            assert polls_with_data >= 1, \
                "no incremental delivery before end-of-stream"
            svc.shutdown()
            return h
        finally:
            if chaos:
                publish_env(None)

    def test_continuous_agg_bit_exact_and_incremental(self, tmp_path):
        self._run(tmp_path)

    def test_kill_mid_stream_recovers_exactly_once(self, tmp_path):
        # the scripted service-injection discipline: kill the streaming
        # operator after N tasks; recovery replays its tape and the merged
        # deltas stay exactly-once
        self._run(tmp_path, inject={"after_tasks": 6, "channels": [(1, 0)]})

    def test_chaos_kills_rearm_on_streams(self, tmp_path):
        from quokka_tpu import obs

        before = obs.REGISTRY.snapshot().get("chaos.kill", 0)
        self._run(tmp_path, chaos="seed=5,kill=2,kill_after=5")
        assert obs.REGISTRY.snapshot().get("chaos.kill", 0) > before

    def test_long_stream_gc_reclaims_control_rows(self, tmp_path):
        """Dynamic half of protocol rule QK015: on a long standing query the
        per-seq control rows (segment log, watermarks, committed-seq
        membership, exec tape, checkpoint history) are reclaimed below the
        recorded-checkpoint floor while the retained tail stays intact —
        and the result is still bit-exact."""
        from quokka_tpu import QuokkaContext, obs
        from quokka_tpu.service import QueryService
        from quokka_tpu.streaming import manifest as smanifest

        rng = np.random.default_rng(17)
        n = 4000
        df = pd.DataFrame({
            "t": np.sort(rng.integers(0, 1000, n)),
            "k": rng.integers(0, 4, n),
            "v": rng.integers(0, 50, n).astype(np.float64),
        })
        rows = [f"{r.t},{r.k},{r.v}\n" for r in df.itertuples(index=False)]
        p = str(tmp_path / "events.csv")
        with open(p, "w") as f:
            f.writelines(rows[:200])
        before = obs.REGISTRY.snapshot().get("stream.gc_rows", 0)
        svc = QueryService(pool_size=2, spill_dir=str(tmp_path / "spill"),
                           exec_config={"fault_tolerance": True,
                                        "checkpoint_interval": 1})
        ctx = QuokkaContext()
        ds = tail_window_agg(
            ctx, TailingCsvReader(p, EV_SCHEMA, "t"), size=100, by="k",
            aggs=[("s", "sum", "v"), ("n", "count", None)])
        h = svc.submit_continuous(ds)
        deltas, appended, t0 = [], 200, time.time()
        while time.time() - t0 < 40:
            if appended < n:  # many small segments -> a long segment log
                with open(p, "a") as f:
                    f.writelines(rows[appended:appended + 100])
                appended += 100
            deltas.extend(h.poll_deltas())
            wm = h.watermark()
            if appended >= n and wm is not None and wm >= float(df.t.max()):
                break
            time.sleep(0.04)
        # session still live: run one final sweep and audit the store
        graph = h._s.graph
        store = graph.store
        smanifest.gc(graph)
        floors = {}
        for info in smanifest._stream_inputs(graph):
            for ch in range(info.channels):
                floor = store.tget("LT", ("gc_floor", info.id, ch), 0)
                floors[(info.id, ch)] = floor
                done = store.smembers("GIT", (info.id, ch))
                for s in range(floor):  # everything below the floor is gone
                    assert store.tget("LT", (info.id, ch, s)) is None
                    assert store.tget("SWM", (info.id, ch, s)) is None
                    assert s not in done
                last = store.tget("LIT", (info.id, ch), -1)
                if last >= 0:  # the newest segment is never dropped
                    assert store.tget("LT", (info.id, ch, last)) is not None
        assert any(f > 0 for f in floors.values()), \
            "gc floor never advanced on a long checkpointed stream"
        pruned_hist = trimmed_tape = False
        for info in graph.actors.values():
            if info.kind != "exec":
                continue
            for ch in range(info.channels):
                hist = [tuple(x) for x in
                        (store.tget("LT", ("ckpts", info.id, ch)) or [])]
                if not hist:
                    continue
                base = store.tget("LT", ("tape_base", info.id, ch), 0)
                trimmed_tape = trimmed_tape or base > 0
                # history is a suffix: everything older than the covering
                # checkpoint was dropped, and the IRT rows went with it
                pruned_hist = pruned_hist or hist[0][0] > 1
                assert [x[0] for x in hist] == sorted(x[0] for x in hist)
        assert trimmed_tape, "no exec tape was ever trimmed"
        assert pruned_hist, "checkpoint history never pruned"
        assert obs.REGISTRY.snapshot().get("stream.gc_rows", 0) > before
        h.stop(timeout=60)
        deltas.extend(h.poll_deltas())
        _assert_exact(_merge_deltas(deltas), _truth(df))
        svc.shutdown()

    def test_manifest_resume_after_service_teardown(self, tmp_path):
        from quokka_tpu import QuokkaContext
        from quokka_tpu.service import QueryService
        from quokka_tpu.service.server import ServiceShutdown

        rng = np.random.default_rng(29)
        n = 3000
        df = pd.DataFrame({
            "t": np.sort(rng.integers(0, 1000, n)),
            "k": rng.integers(0, 4, n),
            "v": rng.integers(0, 50, n).astype(np.float64),
        })
        rows = [f"{r.t},{r.k},{r.v}\n" for r in df.itertuples(index=False)]
        p = str(tmp_path / "events.csv")
        with open(p, "w") as f:
            f.writelines(rows[:400])
        ecfg = {"fault_tolerance": True, "checkpoint_interval": 1}

        def make_stream():
            ctx = QuokkaContext()
            return tail_window_agg(
                ctx, TailingCsvReader(p, EV_SCHEMA, "t"), size=100, by="k",
                aggs=[("s", "sum", "v"), ("n", "count", None)])

        svc = QueryService(pool_size=2, spill_dir=str(tmp_path / "spill"),
                           exec_config=ecfg)
        h = svc.submit_continuous(make_stream())
        mpath = h.manifest_path
        deltas = []
        t0 = time.time()
        appended = 400
        while time.time() - t0 < 30:  # wait for a checkpointed manifest
            if appended < 1200:  # feed several segments pre-teardown
                with open(p, "a") as f:
                    f.writelines(rows[appended:appended + 200])
                appended += 200
            deltas.extend(h.poll_deltas())
            if os.path.exists(mpath) and appended >= 1200:
                break
            time.sleep(0.05)
        assert os.path.exists(mpath), "no manifest before teardown"
        svc.shutdown()  # streaming failure path: durable state preserved
        # the handle stays drainable after teardown: panes that landed in
        # the sink between the last poll and the shutdown (and which the
        # newest checkpoint already covers) are collected here, not lost
        deltas.extend(h.poll_deltas())
        assert isinstance(h.error, ServiceShutdown)
        assert os.path.exists(mpath)
        # the rest of the stream arrives while the service is down
        with open(p, "a") as f:
            f.writelines(rows[1200:])
        svc2 = QueryService(pool_size=2, spill_dir=str(tmp_path / "spill"),
                            exec_config=ecfg)
        # delivered_floor pins the resume point at-or-before the client's
        # captured delta count: a pane the checkpoint already covered but
        # that never crossed the exec->sink edge before teardown re-emits
        # instead of vanishing (the output-commit gap)
        h2 = svc2.submit_continuous(make_stream(), resume_from=mpath,
                                    delivered_floor=len(deltas))
        skipped = sum(r["skipped_segments"]
                      for r in h2.resume_info["inputs"].values())
        assert skipped > 0, "resume recomputed the full stream"
        t0 = time.time()
        while time.time() - t0 < 30:
            wm = h2.watermark()
            if wm is not None and wm >= float(df.t.max()):
                break
            time.sleep(0.05)
        h2.stop(timeout=60)
        deltas.extend(h2.poll_deltas())
        _assert_exact(_merge_deltas(deltas), _truth(df))
        st = svc2.stats()["sessions"]
        svc2.shutdown()
        # clean stop: the manifest (stream complete) is GC'd
        assert not os.path.exists(mpath)

    def test_resume_rejects_different_plan(self, tmp_path):
        from quokka_tpu import QuokkaContext
        from quokka_tpu.service import QueryService
        from quokka_tpu.streaming.manifest import StreamResumeError

        p = str(tmp_path / "events.csv")
        with open(p, "w") as f:
            f.write("1,0,2.0\n900,1,3.0\n")
        ecfg = {"fault_tolerance": True, "checkpoint_interval": 1}
        svc = QueryService(pool_size=1, spill_dir=str(tmp_path / "spill"),
                           exec_config=ecfg)
        ctx = QuokkaContext()
        h = svc.submit_continuous(tail_window_agg(
            ctx, TailingCsvReader(p, EV_SCHEMA, "t"), size=100, by="k",
            aggs=[("s", "sum", "v")]))
        mpath = h.manifest_path
        t0 = time.time()
        while not os.path.exists(mpath) and time.time() - t0 < 20:
            time.sleep(0.05)
        assert os.path.exists(mpath)
        svc.shutdown()
        svc2 = QueryService(pool_size=1, spill_dir=str(tmp_path / "spill"),
                            exec_config=ecfg)
        ctx2 = QuokkaContext()
        different = tail_window_agg(  # different window size = new query
            ctx2, TailingCsvReader(p, EV_SCHEMA, "t"), size=50, by="k",
            aggs=[("s", "sum", "v")])
        with pytest.raises(StreamResumeError):
            svc2.submit_continuous(different, resume_from=mpath)
        svc2.shutdown()

    def test_resume_of_live_stream_refused(self, tmp_path):
        from quokka_tpu import QuokkaContext
        from quokka_tpu.service import QueryService

        p = str(tmp_path / "events.csv")
        with open(p, "w") as f:
            f.write("1,0,2.0\n900,1,3.0\n")
        svc = QueryService(pool_size=1, spill_dir=str(tmp_path / "spill"),
                           exec_config={"fault_tolerance": True,
                                        "checkpoint_interval": 1})
        h = svc.submit_continuous(tail_window_agg(
            QuokkaContext(), TailingCsvReader(p, EV_SCHEMA, "t"),
            size=100, by="k", aggs=[("s", "sum", "v")]))
        t0 = time.time()
        while not os.path.exists(h.manifest_path) and time.time() - t0 < 20:
            time.sleep(0.05)
        # resuming the manifest of a stream STILL RUNNING in this service
        # would run two engines against one namespace: refused loudly
        with pytest.raises(ValueError, match="already running"):
            svc.submit_continuous(tail_window_agg(
                QuokkaContext(), TailingCsvReader(p, EV_SCHEMA, "t"),
                size=100, by="k", aggs=[("s", "sum", "v")]),
                resume_from=h.manifest_path)
        h.stop(timeout=60)
        svc.shutdown()

    def test_handle_dedups_replay_overwrites(self):
        from quokka_tpu.runtime.dataset import ResultDataset

        class _S:  # minimal session stand-in
            pass
        from quokka_tpu.streaming.handle import StreamingHandle

        ds = ResultDataset()
        s = _S()
        s.graph = type("G", (), {})()
        s.graph.result = lambda _a: ds
        s.sink_actor = 0
        h = StreamingHandle.__new__(StreamingHandle)
        h._s = s
        h._cursor = {}
        t1 = pa.table({"x": [1]})
        ds.append(0, t1, seq=0)
        assert [t.to_pylist() for t in h.poll_deltas()] == [[{"x": 1}]]
        ds.append(0, t1, seq=0)  # replay overwrite: same seq, same bytes
        assert h.poll_deltas() == []
        ds.append(0, pa.table({"x": [2]}), seq=1)
        assert [t.to_pylist() for t in h.poll_deltas()] == [[{"x": 2}]]
