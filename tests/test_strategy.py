"""Kernel-strategy matrix (ops/strategy.py): resolution precedence,
calibration persistence, foreign-fingerprint fallback, forced overrides,
bench-honesty validation, and device-asof bit-equality across strategies."""

import json
import os

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from quokka_tpu import config
from quokka_tpu.ops import asof as asof_ops
from quokka_tpu.ops import bridge, kernels
from quokka_tpu.ops import strategy


@pytest.fixture(autouse=True)
def _fresh(monkeypatch):
    """Each test starts with no calibration loaded and no overrides; the
    conftest-level QK_STRATEGY_DIR="" keeps box profiles out."""
    monkeypatch.delenv("QK_KERNEL_STRATEGY", raising=False)
    monkeypatch.delenv("QUOKKA_HASH_TABLES", raising=False)
    monkeypatch.delenv("QUOKKA_HOST_ASOF", raising=False)
    strategy.reset()
    strategy.reset_used()
    yield
    strategy.reset()
    strategy.reset_used()


class TestResolution:
    def test_platform_defaults(self, monkeypatch):
        for plat, want_gb, want_asof in (
            ("cpu", "hashtable", "host"),
            ("gpu", "hashtable", "searchsorted"),
            ("tpu", "sort", "searchsorted"),
        ):
            monkeypatch.setattr(config, "_platform", lambda p=plat: p)
            assert strategy.resolve("groupby") == (want_gb, "default")
            assert strategy.resolve("asof") == (want_asof, "default")
            assert strategy.choice("shuffle") == "masked"

    def test_env_override_wins(self, monkeypatch):
        monkeypatch.setenv("QK_KERNEL_STRATEGY",
                           "groupby=sort, asof=searchsorted")
        monkeypatch.setenv("QUOKKA_HASH_TABLES", "1")  # loses to QK_KERNEL_
        assert strategy.resolve("groupby") == ("sort", "env")
        assert strategy.resolve("asof") == ("searchsorted", "env")
        # unlisted op falls through to the legacy env
        assert strategy.resolve("join_build") == ("hashtable", "legacy-env")

    def test_env_override_rejects_unknown(self, monkeypatch):
        monkeypatch.setenv("QK_KERNEL_STRATEGY", "groupby=btree")
        with pytest.raises(strategy.StrategyError, match="btree"):
            strategy.choice("groupby")
        monkeypatch.setenv("QK_KERNEL_STRATEGY", "quantum=sort")
        with pytest.raises(strategy.StrategyError, match="quantum"):
            strategy.choice("groupby")

    def test_legacy_envs_keep_meaning(self, monkeypatch):
        monkeypatch.setenv("QUOKKA_HASH_TABLES", "0")
        assert strategy.choice("groupby") == "sort"
        assert strategy.choice("join_build") == "sort"
        monkeypatch.setenv("QUOKKA_HOST_ASOF", "1")
        assert strategy.choice("asof") == "host"
        monkeypatch.setenv("QUOKKA_HOST_ASOF", "0")
        assert strategy.choice("asof") != "host"
        # config delegates answer the same question
        assert config.use_hash_tables() is False
        assert config.use_host_asof() is False


class TestCalibrationPersistence:
    def test_round_trip(self, monkeypatch, tmp_path):
        monkeypatch.setenv("QK_STRATEGY_DIR", str(tmp_path))
        strategy.reset()
        res = strategy.calibrate(rows=2048, reps=1)
        # shuffle and asof_probe are never picked by calibration (pipeline
        # properties, not kernel walls — see calibrate(); shuffle is still
        # timed for the profile's information)
        assert set(res["choices"]) == set(strategy.OPS) - {"shuffle",
                                                           "asof_probe"}
        for op, ch in res["choices"].items():
            assert ch in strategy.OPS[op]
        assert res["timings_s"]["shuffle"].keys() == {"masked", "compacted"}
        # a fresh resolution state answers from the persisted profile
        strategy.reset()
        assert {op: strategy.choice(op) for op in res["choices"]} \
            == res["choices"]
        assert strategy.resolve("shuffle") == ("masked", "default")
        files = list(tmp_path.iterdir())
        assert len(files) == 1
        prof = json.loads(files[0].read_text())
        assert prof["fingerprint"] == strategy._fingerprint()
        assert prof["choices"] == res["choices"]
        # every candidate that ran has a timing
        assert prof["timings_s"]["groupby"].keys() == {"sort", "hashtable"}

    def test_foreign_fingerprint_falls_back_to_defaults(
            self, monkeypatch, tmp_path):
        monkeypatch.setenv("QK_STRATEGY_DIR", str(tmp_path))
        strategy.reset()
        prof = {"version": strategy._CALIB_VERSION,
                "fingerprint": "tpu-8x-deadbeef0000",
                "choices": {op: strategy.OPS[op][0] for op in strategy.OPS}}
        (tmp_path / f"{strategy._fingerprint()}.json").write_text(
            json.dumps(prof))
        # fingerprint inside the file is foreign -> ignored wholesale
        assert set(strategy.sources().values()) == {"default"}

    def test_corrupt_profile_ignored(self, monkeypatch, tmp_path):
        monkeypatch.setenv("QK_STRATEGY_DIR", str(tmp_path))
        strategy.reset()
        (tmp_path / f"{strategy._fingerprint()}.json").write_text("{not json")
        assert set(strategy.sources().values()) == {"default"}
        strategy.reset()
        bad = {"version": strategy._CALIB_VERSION,
               "fingerprint": strategy._fingerprint(),
               "choices": {"groupby": "btree"}}
        (tmp_path / f"{strategy._fingerprint()}.json").write_text(
            json.dumps(bad))
        assert strategy.resolve("groupby")[1] == "default"

    def test_ensure_calibrated_loads_without_rerun(self, monkeypatch,
                                                   tmp_path):
        monkeypatch.setenv("QK_STRATEGY_DIR", str(tmp_path))
        strategy.reset()
        first = strategy.calibrate(rows=2048, reps=1)["choices"]
        strategy.reset()
        # a second process would load, not re-bench: forbid calibration and
        # the answer must still be the persisted choices
        monkeypatch.setenv("QK_STRATEGY_CALIBRATE", "0")
        assert strategy.ensure_calibrated() == first


class TestHonesty:
    def test_note_used_and_snapshot(self):
        strategy.note_used("asof", "searchsorted")
        strategy.note_used("groupby", "hashtable")
        assert strategy.used_snapshot() == {
            "asof": "searchsorted", "groupby": "hashtable"}
        strategy.reset_used()
        assert strategy.used_snapshot() == {}

    def test_invalid_for_platform(self):
        assert strategy.invalid_for_platform("tpu", "asof", "host")
        assert strategy.invalid_for_platform("gpu", "asof", "host")
        assert strategy.invalid_for_platform("cpu", "asof", "host") is None
        assert strategy.invalid_for_platform(
            "tpu", "asof", "searchsorted") is None
        assert strategy.invalid_for_platform("cpu", "groupby", "btree")
        assert strategy.invalid_for_platform("cpu", "quantum", "sort")

    def test_join_and_shuffle_record_used(self, monkeypatch):
        r = np.random.default_rng(3)
        n = 500
        probe = bridge.arrow_to_device(pa.table({
            "k": r.integers(0, 100, n).astype(np.int64),
            "v": r.uniform(0, 1, n)}))
        build = bridge.arrow_to_device(pa.table({
            "k": np.arange(100, dtype=np.int64),
            "w": r.uniform(0, 1, 100)}))
        from quokka_tpu.ops import join as join_ops

        for forced in ("hashtable", "sort"):
            strategy.reset_used()
            monkeypatch.setenv("QK_KERNEL_STRATEGY", f"join_build={forced}")
            build2 = bridge.arrow_to_device(pa.table({
                "k": np.arange(100, dtype=np.int64),
                "w": r.uniform(0, 1, 100)}))
            join_ops.hash_join_pk(probe, build2, ["k"], ["k"], "inner",
                                  ["w"])
            assert strategy.used_snapshot()["join_build"] == forced
        strategy.reset_used()
        monkeypatch.setenv("QK_KERNEL_STRATEGY", "shuffle=masked")
        big = bridge.arrow_to_device(pa.table({
            "k": r.integers(0, 1 << 20, 1 << 17).astype(np.int64)}))
        pids = kernels.partition_ids(big, ["k"], 4)
        kernels.split_by_partition(big, pids, 4)
        assert strategy.used_snapshot()["shuffle"] == "masked"

    def test_multiple_kernels_per_op_all_recorded(self):
        """A mesh query's timed shard kernel and its coordinator-side
        recombine may run DIFFERENT groupby kernels; the snapshot must name
        both, not whichever dispatched last."""
        strategy.note_used("groupby", "sort")
        strategy.note_used("groupby", "hashtable")
        strategy.note_used("groupby", "sort")  # dedup, no re-count
        assert strategy.used_snapshot() == {"groupby": "hashtable+sort"}
        assert strategy.invalid_for_platform(
            "tpu", "groupby", "hashtable+sort") is None
        # every component must be runnable: host asof hiding in a
        # multi-value is still gated off non-CPU platforms
        assert strategy.invalid_for_platform("tpu", "asof", "host+sort")
        assert strategy.invalid_for_platform("cpu", "groupby", "sort+btree")


def _ticks(seed, n_t=400, n_q=900, dup_times=True):
    r = np.random.default_rng(seed)
    span = 50 if dup_times else 1 << 20  # coarse span -> many exact ties
    tt = np.sort(r.integers(0, span, n_t)).astype(np.int64)
    qt = np.sort(r.integers(0, span, n_q)).astype(np.int64)
    syms = np.array(["A", "B", "C"])
    trades = pa.table({"time": tt, "symbol": syms[r.integers(0, 3, n_t)],
                       "size": r.integers(1, 9, n_t).astype(np.int32)})
    quotes = pa.table({"time": qt, "symbol": syms[r.integers(0, 3, n_q)],
                       "bid": np.arange(n_q, dtype=np.float64)})
    return trades, quotes


class TestAsofStrategyEquality:
    """The satellite contract: device searchsorted == host native == device
    sort kernel, bit for bit, fwd + bwd, including duplicate timestamps
    (tie-break pins WHICH quote), unmatched rows, and empty sides."""

    @pytest.mark.parametrize("direction", ["backward", "forward"])
    @pytest.mark.parametrize("dup_times", [True, False])
    def test_three_strategies_bit_equal(self, direction, dup_times):
        trades, quotes = _ticks(17, dup_times=dup_times)
        frames = {}
        for strat in ("host", "sort", "searchsorted"):
            tb = bridge.arrow_to_device(trades)
            qb = bridge.arrow_to_device(quotes)
            out = asof_ops.asof_join(
                tb, qb, "time", "time", ["symbol"], ["symbol"], ["bid"],
                direction=direction, strategy=strat)
            matched = out.columns.pop("__asof_matched__").data
            out = kernels.compact(kernels.apply_mask(out, matched))
            df = bridge.device_to_arrow(out).to_pandas()
            frames[strat] = df.sort_values(
                ["time", "symbol", "size", "bid"]).reset_index(drop=True)
        pd.testing.assert_frame_equal(frames["host"], frames["searchsorted"])
        pd.testing.assert_frame_equal(frames["sort"], frames["searchsorted"])
        # and all of them match the pandas oracle
        exp = pd.merge_asof(
            trades.to_pandas(), quotes.to_pandas(), on="time", by="symbol",
            direction=direction).dropna(subset=["bid"])
        exp = exp.sort_values(
            ["time", "symbol", "size", "bid"]).reset_index(drop=True)
        np.testing.assert_array_equal(
            frames["searchsorted"].bid.to_numpy(), exp.bid.to_numpy())

    @pytest.mark.parametrize("direction", ["backward", "forward"])
    def test_empty_quotes_all_unmatched(self, direction):
        trades, _ = _ticks(5)
        qb = bridge.arrow_to_device(pa.table({
            "time": np.array([], dtype=np.int64),
            "symbol": pa.array([], type=pa.string()),
            "bid": np.array([], dtype=np.float64)}))
        tb = bridge.arrow_to_device(trades)
        out = asof_ops.asof_join(
            tb, qb, "time", "time", ["symbol"], ["symbol"], ["bid"],
            direction=direction, strategy="searchsorted")
        assert not np.asarray(out.columns["__asof_matched__"].data).any()

    def test_empty_trades(self):
        _, quotes = _ticks(6)
        tb = bridge.arrow_to_device(pa.table({
            "time": np.array([], dtype=np.int64),
            "symbol": pa.array([], type=pa.string()),
            "size": np.array([], dtype=np.int32)}))
        qb = bridge.arrow_to_device(quotes)
        out = asof_ops.asof_join(
            tb, qb, "time", "time", ["symbol"], ["symbol"], ["bid"],
            strategy="searchsorted")
        assert int(np.asarray(out.columns["__asof_matched__"].data)
                   .sum()) == 0

    @pytest.mark.parametrize("direction", ["backward", "forward"])
    def test_mixed_time_dtypes_match_sort_path(self, direction):
        """float trade times vs int quote times: the quote side must be
        cast to the TRADE dtype before the search (the sort kernel's
        convention) — casting the probe side instead truncated 5.7 -> 5 and
        forward-matched a quote EARLIER than the trade."""
        tb = bridge.arrow_to_device(pa.table({
            "time": np.array([5.7, 0.2, 8.0]),
            "symbol": ["A", "A", "A"]}))
        frames = {}
        for strat in ("sort", "searchsorted"):
            qb = bridge.arrow_to_device(pa.table({
                "time": np.array([5, 6, 9], dtype=np.int64),
                "symbol": ["A", "A", "A"],
                "bid": np.array([100.0, 200.0, 300.0])}))
            out = asof_ops.asof_join(
                tb, qb, "time", "time", ["symbol"], ["symbol"], ["bid"],
                direction=direction, strategy=strat)
            matched = out.columns.pop("__asof_matched__").data
            out = kernels.compact(kernels.apply_mask(out, matched))
            df = bridge.device_to_arrow(out).to_pandas()
            frames[strat] = df.sort_values("time").reset_index(drop=True)
        pd.testing.assert_frame_equal(frames["sort"], frames["searchsorted"])
        want = ({5.7: 100.0, 0.2: None, 8.0: 200.0} if direction == "backward"
                else {5.7: 200.0, 0.2: 100.0, 8.0: 300.0})
        got = dict(zip(frames["searchsorted"].time,
                       frames["searchsorted"].bid))
        assert got == {t: b for t, b in want.items() if b is not None}

    def test_quote_sort_cached_on_batch(self):
        trades, quotes = _ticks(8)
        tb = bridge.arrow_to_device(trades)
        qb = bridge.arrow_to_device(quotes)
        asof_ops.asof_join(tb, qb, "time", "time", ["symbol"], ["symbol"],
                           ["bid"], strategy="searchsorted")
        cache = qb._asof_ss_cache
        assert len(cache) == 1
        key = next(iter(cache))
        before = cache[key]
        asof_ops.asof_join(tb, qb, "time", "time", ["symbol"], ["symbol"],
                           ["bid"], direction="forward",
                           strategy="searchsorted")
        # both directions share the one cached quote sort
        assert cache[key] is before and len(cache) == 1

    def test_forced_host_falls_back_on_device_when_declined(self):
        """int trade times vs float quote times: the native merge declines
        (encodings not comparable); the recorded strategy must be the
        device kernel that actually answered."""
        strategy.reset_used()
        tb = bridge.arrow_to_device(pa.table({
            "time": np.array([1, 5, 9], dtype=np.int64),
            "symbol": ["A", "A", "A"]}))
        qb = bridge.arrow_to_device(pa.table({
            "time": np.array([0.5, 4.5, 8.5]),
            "symbol": ["A", "A", "A"],
            "bid": np.array([1.0, 2.0, 3.0])}))
        out = asof_ops.asof_join(
            tb, qb, "time", "time", ["symbol"], ["symbol"], ["bid"],
            strategy="host")
        assert np.asarray(
            out.columns["__asof_matched__"].data)[:3].all()
        assert strategy.used_snapshot()["asof"] == "searchsorted"
