"""Device scan (buffer-pool) cache: warm hits, file-rewrite invalidation."""

import os
import time

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from quokka_tpu import QuokkaContext
from quokka_tpu.runtime import scancache


@pytest.fixture(autouse=True)
def fresh_cache():
    scancache.clear()
    yield
    scancache.clear()


def _write(path, seed):
    rng = np.random.default_rng(seed)
    df = pd.DataFrame({"k": rng.choice(["a", "b"], 1000), "v": rng.uniform(0, 1, 1000)})
    pq.write_table(pa.Table.from_pandas(df, preserve_index=False), path)
    return df


def _q(path):
    ctx = QuokkaContext(io_channels=1, exec_channels=1)
    return (
        ctx.read_parquet(path).groupby("k").agg_sql("sum(v) as sv").collect()
        .sort_values("k").reset_index(drop=True)
    )


def test_warm_hit_and_rewrite_invalidation(tmp_path):
    p = str(tmp_path / "t.parquet")
    df = _write(p, 1)
    out1 = _q(p)
    stats = scancache.GLOBAL.stats()
    assert stats["entries"] >= 1 and stats["misses"] >= 1
    out2 = _q(p)
    stats2 = scancache.GLOBAL.stats()
    assert stats2["hits"] >= 1, stats2
    pd.testing.assert_frame_equal(out1, out2)
    want = df.groupby("k").agg(sv=("v", "sum")).reset_index()
    assert np.allclose(out2["sv"].to_numpy(), want["sv"].to_numpy())

    # rewrite the file: cache must not serve stale rows
    time.sleep(0.01)
    df3 = _write(p, 2)
    out3 = _q(p)
    want3 = df3.groupby("k").agg(sv=("v", "sum")).reset_index()
    assert np.allclose(out3["sv"].to_numpy(), want3["sv"].to_numpy())


def test_cap_and_disable(tmp_path):
    p = str(tmp_path / "t.parquet")
    _write(p, 3)
    small = scancache.ScanCache(cap_bytes=1)  # nothing fits
    old = scancache.GLOBAL
    scancache.GLOBAL = small
    try:
        _q(p)
        assert small.stats()["entries"] == 0
    finally:
        scancache.GLOBAL = old

    disabled = scancache.ScanCache(cap_bytes=0)
    assert not disabled.enabled
