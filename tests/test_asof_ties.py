"""As-of tie-break semantics: among quotes sharing (key, time), backward
picks the LAST by original order and forward picks the FIRST — pandas
merge_asof semantics, which both the native host merge
(native/columnar.cpp qk_asof_*) and the device sort+scan kernel
(ops/asof._asof_match tie key) must reproduce identically."""

import numpy as np
import pandas as pd
import pytest

import jax.numpy as jnp

from quokka_tpu.ops import asof as asof_ops
from quokka_tpu.ops import bridge, kernels


def _ticks_with_ties(seed=9, n_trades=300, n_quotes=600):
    r = np.random.default_rng(seed)
    # coarse times force many exact collisions on (symbol, time)
    tt = np.sort(r.integers(0, 40, n_trades)).astype(np.int64)
    qt = np.sort(r.integers(0, 40, n_quotes)).astype(np.int64)
    syms = np.array(["A", "B"])
    import pyarrow as pa

    trades = pa.table({"time": tt, "symbol": syms[r.integers(0, 2, n_trades)],
                       "size": r.integers(1, 9, n_trades).astype(np.int32)})
    quotes = pa.table({"time": qt, "symbol": syms[r.integers(0, 2, n_quotes)],
                       "bid": np.arange(n_quotes, dtype=np.float64)})
    return trades, quotes


@pytest.mark.parametrize("direction", ["backward", "forward"])
@pytest.mark.parametrize("host", ["1", "0"])
def test_tie_break_matches_pandas(direction, host, monkeypatch):
    monkeypatch.setenv("QUOKKA_HOST_ASOF", host)
    trades, quotes = _ticks_with_ties()
    tb = bridge.arrow_to_device(trades)
    qb = bridge.arrow_to_device(quotes)
    out = asof_ops.asof_join(
        tb, qb, "time", "time", ["symbol"], ["symbol"], ["bid"],
        direction=direction,
    )
    out = kernels.apply_mask(out, out.columns.pop("__asof_matched__").data)
    got = bridge.device_to_arrow(kernels.compact(out)).to_pandas()
    exp = pd.merge_asof(
        trades.to_pandas(), quotes.to_pandas(), on="time", by="symbol",
        direction=direction,
    ).dropna(subset=["bid"])
    key = ["time", "symbol", "size"]
    got = got.sort_values(key).reset_index(drop=True)
    exp = exp.sort_values(key).reset_index(drop=True)
    assert len(got) == len(exp), (direction, host)
    # bid doubles as the quote's original index, so equality pins WHICH
    # tied quote was chosen, not just a value match
    np.testing.assert_array_equal(got.bid.to_numpy(), exp.bid.to_numpy())


def test_host_and_device_paths_agree(monkeypatch):
    trades, quotes = _ticks_with_ties(seed=123)
    outs = {}
    for host in ("1", "0"):
        monkeypatch.setenv("QUOKKA_HOST_ASOF", host)
        tb = bridge.arrow_to_device(trades)
        qb = bridge.arrow_to_device(quotes)
        for direction in ("backward", "forward"):
            out = asof_ops.asof_join(
                tb, qb, "time", "time", ["symbol"], ["symbol"], ["bid"],
                direction=direction,
            )
            m = out.columns.pop("__asof_matched__").data
            out = kernels.apply_mask(out, m)
            df = bridge.device_to_arrow(kernels.compact(out)).to_pandas()
            outs[(host, direction)] = df.sort_values(
                ["time", "symbol", "size"]).reset_index(drop=True)
    for direction in ("backward", "forward"):
        a, b = outs[("1", direction)], outs[("0", direction)]
        pd.testing.assert_frame_equal(a, b)


def test_mixed_time_dtypes_fall_back(monkeypatch):
    """int trade times vs float quote times: the host path must decline
    (encodings not comparable) and the device kernel must still answer."""
    monkeypatch.setenv("QUOKKA_HOST_ASOF", "1")
    import pyarrow as pa

    trades = pa.table({"time": np.array([1, 5, 9], dtype=np.int64),
                       "symbol": ["A", "A", "A"]})
    quotes = pa.table({"time": np.array([0.5, 4.5, 8.5]),
                       "symbol": ["A", "A", "A"],
                       "bid": np.array([1.0, 2.0, 3.0])})
    tb = bridge.arrow_to_device(trades)
    qb = bridge.arrow_to_device(quotes)
    assert asof_ops._asof_match_host(
        tb, qb, "time", "time", ["symbol"], ["symbol"], "backward") is None
    out = asof_ops.asof_join(
        tb, qb, "time", "time", ["symbol"], ["symbol"], ["bid"])
    m = np.asarray(out.columns["__asof_matched__"].data)[:3]
    assert m.tolist() == [True, True, True]
    np.testing.assert_allclose(
        np.asarray(out.columns["bid"].data)[:3], [1.0, 2.0, 3.0])
