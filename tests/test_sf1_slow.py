"""SF1 slow tier (run with `pytest -m slow`): the TPC-H oracle queries at
SF1 with PRODUCTION spill thresholds (no monkeypatching — SURVEY.md §4's
"TPC-H SF0.01..1 vs the correctness oracle" at the top of the range), plus
shapes big enough that the disk tier engages naturally: an external sort of
SF1 lineitem (6M rows > config.SPILL_SORT_ROWS) and a grace join with a
build side past config.SPILL_JOIN_BUILD_ROWS.  SPILL_EVENTS asserts the
spill paths actually ran."""

import numpy as np
import pandas as pd
import pytest

from quokka_tpu import QuokkaContext, config
from quokka_tpu.executors import sql_execs

import tpch_data
import test_tpch
import test_tpch2

pytestmark = pytest.mark.slow

SF = 1.0


@pytest.fixture(scope="module")
def env(tmp_path_factory):
    root = tmp_path_factory.mktemp("tpch_sf1")
    tables = tpch_data.generate(sf=SF, seed=7)
    # production-sized row groups: the default 4096-row test groups would
    # turn SF1 into ~1500 batches of pure per-batch overhead
    paths = tpch_data.write_parquet_dir(tables, str(root), row_group_size=1 << 20)
    ctx = QuokkaContext(io_channels=3, exec_channels=2)
    dfs = {k: t.to_pandas() for k, t in tables.items()}
    return ctx, paths, dfs


def test_q1_sf1(env):
    test_tpch.test_q1(env)


def test_q3_sf1(env):
    test_tpch.test_q3(env)


def test_q5_sf1(env):
    test_tpch.test_q5(env)


@pytest.fixture(scope="module")
def env_mid(tmp_path_factory):
    """Q18/Q21 are many-join + multi-distinct shapes: at SF1 a single run
    exceeds half an hour on a 1-core host, so they get a mid scale — still
    ~80x the default test tier and big enough for real batch/shuffle
    traffic, at production thresholds."""
    root = tmp_path_factory.mktemp("tpch_sf_mid")
    tables = tpch_data.generate(sf=0.25, seed=7)
    paths = tpch_data.write_parquet_dir(tables, str(root), row_group_size=1 << 18)
    ctx = QuokkaContext(io_channels=3, exec_channels=2)
    dfs = {k: t.to_pandas() for k, t in tables.items()}
    return ctx, paths, dfs


def test_q18_sf_mid(env_mid):
    test_tpch2.test_q18(env_mid)


def test_q21_sf_mid(env_mid):
    test_tpch2.test_q21(env_mid)


def test_external_sort_spills_at_production_threshold(env):
    ctx, paths, dfs = env
    l = dfs["lineitem"]
    assert len(l) > config.SPILL_SORT_ROWS, (
        "fixture must exceed the production sort threshold for this test "
        f"to mean anything ({len(l)} <= {config.SPILL_SORT_ROWS})"
    )
    before = sql_execs.SPILL_EVENTS
    got = (
        ctx.read_parquet(paths["lineitem"],
                         columns=["l_orderkey", "l_extendedprice"])
        .sort(["l_extendedprice", "l_orderkey"], descending=[True, False])
        .collect()
    )
    assert sql_execs.SPILL_EVENTS > before, (
        "SF1 sort never crossed the production spill threshold"
    )
    exp = l.sort_values(
        ["l_extendedprice", "l_orderkey"], ascending=[False, True]
    ).reset_index(drop=True)
    assert len(got) == len(exp)
    np.testing.assert_allclose(
        got.l_extendedprice.to_numpy(), exp.l_extendedprice.to_numpy()
    )
    # spot-check full row alignment on the extremes (ties broken by orderkey)
    np.testing.assert_array_equal(
        got.l_orderkey.head(1000).to_numpy(), exp.l_orderkey.head(1000).to_numpy()
    )


def test_grace_join_spills_at_production_threshold(env):
    ctx, paths, dfs = env
    l = dfs["lineitem"]
    assert len(l) > config.SPILL_JOIN_BUILD_ROWS
    before = sql_execs.SPILL_EVENTS
    # lineitem self-join on orderkey: the BUILD side accumulates all 6M rows
    # and must partition to disk (grace mode) at the production threshold.
    # optimize=False pins probe/build as written (the optimizer would pick
    # the small side as build and never spill); ONE exec channel so the build
    # is not halved below the threshold by the hash split; the probe side is
    # filtered (~2% of rows) so the join OUTPUT stays bounded while the
    # build spills.
    ctx2 = QuokkaContext(io_channels=3, exec_channels=1, optimize=False)
    left = (
        ctx2.read_parquet(paths["lineitem"], columns=["l_orderkey", "l_quantity"])
        .filter_sql("l_quantity >= 49")
    )
    right = (
        ctx2.read_parquet(paths["lineitem"],
                          columns=["l_orderkey", "l_extendedprice"])
        .rename({"l_orderkey": "r_orderkey"})
    )
    got = (
        left.join(right, left_on="l_orderkey", right_on="r_orderkey")
        .agg_sql("count(*) as n, sum(l_extendedprice) as se")
        .collect()
    )
    assert sql_execs.SPILL_EVENTS > before, (
        "SF1 join build never crossed the production spill threshold"
    )
    lp = l[l.l_quantity >= 49]
    sizes = l.groupby("l_orderkey").size()
    exp_n = int(sizes.loc[lp.l_orderkey].sum())
    assert int(got.n[0]) == exp_n
    per_order = l.groupby("l_orderkey").l_extendedprice.sum()
    exp_se = float(per_order.loc[lp.l_orderkey].sum())
    np.testing.assert_allclose(float(got.se[0]), exp_se, rtol=1e-6)
