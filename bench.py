"""Benchmark: TPC-H Q1 / Q3 / Q5 through the full engine on the local chip.

Prints one JSON line per query plus a FINAL summary line (the line of
record — the driver parses the last JSON line):

  {"metric": "tpch_q135_speedup_geomean_per_chip", "value": N, "unit": "x",
   "vs_baseline": N, "detail": {...}}

Baseline derivation (BASELINE.md): the reference's captured TPC-H run
(`blocking-runtime`, SF100 on 4 workers, 3 repeats each) shows

  Q1 ~= 9.56 s   (blocking-runtime:27,53,79)
  Q3 ~= 14.58 s  (blocking-runtime:113,147,181 — the l_orderkey/o_orderdate/
                  o_shippriority/revenue result block confirms the query)
  Q5 ~= 22.08 s  (blocking-runtime:220,259,298 — nation/revenue block)

Normalised to per-worker-per-SF seconds (work scales linearly with SF):
baseline_seconds(q, sf) = t_ref * 4 workers / 100 SF * sf.  A query's
speedup = baseline_seconds / our_seconds on ONE chip; vs_baseline >= 1.0
means one chip matches one reference worker's per-SF efficiency.  For Q1
this is arithmetically identical to the GB/s-scanned-per-chip metric of
earlier rounds (0.654 GB/s/worker), which is still emitted for continuity.

Robustness: the tunneled dev TPU runtime can WEDGE mid-RPC (a blocked
tcp_recvmsg that never returns), which would hang this process forever.  All
device work therefore runs in a SUPERVISED CHILD process with a hard timeout:
probe -> measure on TPU; on wedge/timeout the child is killed and the
measurement retries once, then falls back to CPU -- loudly (platform +
tpu_fallback_to_cpu fields; the value still parses but cannot be mistaken for
a TPU number).
"""

import json
import math
import os
import subprocess
import sys
import time

BASELINE_GBPS_PER_WORKER = 0.654
# blocking-runtime per-query averages (seconds, SF100, 4 workers)
REF_SECONDS_SF100_4W = {"q1": 9.559, "q3": 14.579, "q5": 22.081}
# asof join + sum: 1.3B quotes x 250M trades in ~35 s on 4 workers
# (BASELINE.md / blog/orderedstreams.md:51) => rows/s per worker
REF_ASOF_ROWS_PER_S_PER_WORKER = (1.3e9 + 2.5e8) / 35.0 / 4.0

# Plan-invariant verification (analysis/planck.py QK021-QK024) is default-ON
# for the bench: every optimizer pass of every benched plan is checked, and
# the per-query cost is reported as detail.plan_verify (plan-time only —
# never on the push path; acceptance is <= 5 ms per plan).
os.environ.setdefault("QK_PLAN_VERIFY", "1")

SF = float(os.environ.get("QUOKKA_BENCH_SF", "1.0"))
CACHE = os.environ.get("QUOKKA_BENCH_CACHE", "/tmp/quokka_tpu_bench")
# generous: first compile of the full kernel set over the remote-compile
# tunnel is minutes; a healthy steady-state run is seconds
MEASURE_TIMEOUT = int(os.environ.get("QUOKKA_BENCH_TIMEOUT", "2400"))

BENCH_TABLES = ["lineitem", "orders", "customer", "supplier", "nation", "region"]

# tick-backtest scale (rows), ~the reference's 5.2:1 quote:trade ratio
ASOF_QUOTES = int(6_000_000 * SF)
ASOF_TRADES = int(1_150_000 * SF)
ASOF_SYMBOLS = 100


def ensure_data():
    """Generate-and-cache every table Q1/Q3/Q5 touch plus the tick-backtest
    trades/quotes; returns {name: path}."""
    os.makedirs(CACHE, exist_ok=True)
    paths = {
        t: os.path.join(CACHE, f"{t}_sf{SF}.parquet") for t in BENCH_TABLES
    }
    if not all(os.path.exists(p) for p in paths.values()):
        sys.path.insert(0, os.path.join(os.path.dirname(__file__), "tests"))
        import tpch_data

        tables = tpch_data.generate(sf=SF, seed=42)
        import pyarrow.parquet as pq

        for t, p in paths.items():
            if not os.path.exists(p):
                pq.write_table(tables[t], p, row_group_size=1 << 20)
    for t, n_rows, cols in (
        ("trades", ASOF_TRADES, "t"),
        ("quotes", ASOF_QUOTES, "q"),
    ):
        p = os.path.join(CACHE, f"{t}_sf{SF}.parquet")
        paths[t] = p
        if not os.path.exists(p):
            import numpy as np
            import pyarrow as pa
            import pyarrow.parquet as pq

            r = np.random.default_rng(7 if cols == "t" else 8)
            span = 86_400_000  # one trading day in ms
            times = np.sort(r.integers(0, span, n_rows)).astype(np.int64)
            syms = np.array([f"S{i:03d}" for i in range(ASOF_SYMBOLS)])
            table = {"time": times,
                     "symbol": syms[r.integers(0, ASOF_SYMBOLS, n_rows)]}
            if cols == "t":
                table["size"] = r.integers(1, 500, n_rows).astype(np.int64)
            else:
                table["bid"] = r.uniform(10, 500, n_rows).round(3)
            pq.write_table(pa.table(table), p, row_group_size=1 << 20)
    return paths


Q1_COLS = [
    "l_returnflag",
    "l_linestatus",
    "l_quantity",
    "l_extendedprice",
    "l_discount",
    "l_tax",
    "l_shipdate",
]

Q1_AGGS = (
    "sum(l_quantity) as sum_qty, "
    "sum(l_extendedprice) as sum_base_price, "
    "sum(l_extendedprice * (1 - l_discount)) as sum_disc_price, "
    "sum(l_extendedprice * (1 - l_discount) * (1 + l_tax)) as sum_charge, "
    "avg(l_quantity) as avg_qty, "
    "avg(l_extendedprice) as avg_price, "
    "avg(l_discount) as avg_disc, "
    "count(*) as count_order"
)


def _ctx():
    from quokka_tpu import QuokkaContext

    return QuokkaContext(io_channels=3, exec_channels=2)


def build_q1(paths, ctx=None):
    ctx = ctx or _ctx()
    return (
        ctx.read_parquet(paths["lineitem"], columns=Q1_COLS)
        .filter_sql("l_shipdate <= date '1998-12-01' - interval '90' day")
        .groupby(["l_returnflag", "l_linestatus"])
        .agg_sql(Q1_AGGS)
    )


def run_q1(paths):
    q = build_q1(paths)
    t0 = time.time()
    df = q.collect()
    dt = time.time() - t0
    assert len(df) == 6, df
    return dt


def build_q3(paths, ctx=None):
    from quokka_tpu.expression import col

    ctx = ctx or _ctx()
    lineitem = ctx.read_parquet(
        paths["lineitem"],
        columns=["l_orderkey", "l_shipdate", "l_extendedprice", "l_discount"],
    )
    orders = ctx.read_parquet(
        paths["orders"],
        columns=["o_orderkey", "o_custkey", "o_orderdate", "o_shippriority"],
    )
    customer = ctx.read_parquet(
        paths["customer"], columns=["c_custkey", "c_mktsegment"]
    )
    return (
        lineitem.filter_sql("l_shipdate > date '1995-03-15'")
        .join(
            orders.filter_sql("o_orderdate < date '1995-03-15'"),
            left_on="l_orderkey",
            right_on="o_orderkey",
        )
        .join(
            customer.filter(col("c_mktsegment") == "BUILDING"),
            left_on="o_custkey",
            right_on="c_custkey",
        )
        .groupby(["l_orderkey", "o_orderdate", "o_shippriority"])
        .agg_sql("sum(l_extendedprice * (1 - l_discount)) as revenue")
        .top_k(["revenue"], 10, [True])
    )


def run_q3(paths):
    q = build_q3(paths)
    t0 = time.time()
    df = q.collect()
    dt = time.time() - t0
    assert 0 < len(df) <= 10, df
    return dt


def build_q5(paths, ctx=None):
    from quokka_tpu.expression import col

    ctx = ctx or _ctx()
    lineitem = ctx.read_parquet(
        paths["lineitem"],
        columns=["l_orderkey", "l_suppkey", "l_extendedprice", "l_discount"],
    )
    orders = ctx.read_parquet(
        paths["orders"], columns=["o_orderkey", "o_custkey", "o_orderdate"]
    )
    customer = ctx.read_parquet(
        paths["customer"], columns=["c_custkey", "c_nationkey"]
    )
    supplier = ctx.read_parquet(
        paths["supplier"], columns=["s_suppkey", "s_nationkey"]
    )
    nation = ctx.read_parquet(
        paths["nation"], columns=["n_nationkey", "n_name", "n_regionkey"]
    )
    region = ctx.read_parquet(paths["region"], columns=["r_regionkey", "r_name"])
    return (
        lineitem.join(
            orders.filter_sql(
                "o_orderdate >= date '1994-01-01' and o_orderdate < date '1995-01-01'"
            ),
            left_on="l_orderkey",
            right_on="o_orderkey",
        )
        .join(customer, left_on="o_custkey", right_on="c_custkey")
        .join(
            supplier,
            left_on=["l_suppkey", "c_nationkey"],
            right_on=["s_suppkey", "s_nationkey"],
        )
        .join(nation, left_on="c_nationkey", right_on="n_nationkey")
        .join(
            region.filter(col("r_name") == "ASIA"),
            left_on="n_regionkey",
            right_on="r_regionkey",
        )
        .groupby("n_name")
        .agg_sql("sum(l_extendedprice * (1 - l_discount)) as revenue")
    )


def run_q5(paths):
    q = build_q5(paths)
    t0 = time.time()
    df = q.collect()
    dt = time.time() - t0
    assert 0 < len(df) <= 5, df
    return dt


def build_asof(paths, ctx=None):
    """Tick backtest core: asof-join trades<-quotes by symbol + grouped sum
    (BASELINE.json config 4; the reference's apps/time-series headline —
    blog/orderedstreams.md:51)."""
    ctx = ctx or _ctx()
    t = ctx.read_sorted_parquet(paths["trades"], sorted_by="time")
    q = ctx.read_sorted_parquet(paths["quotes"], sorted_by="time")
    return (
        t.join_asof(q, on="time", by="symbol")
        .with_columns_sql("bid * size as notional")
        .groupby("symbol")
        .agg_sql("sum(notional) as total, count(*) as n")
    )


def run_asof(paths):
    qry = build_asof(paths)
    t0 = time.time()
    df = qry.collect()
    dt = time.time() - t0
    assert 0 < len(df) <= ASOF_SYMBOLS, df
    return dt


QUERIES = {"q1": run_q1, "q3": run_q3, "q5": run_q5}
BUILDERS = {"q1": build_q1, "q3": build_q3, "q5": build_q5}


# -- skewjoin: adaptive-vs-static on a zipfian-keyed build side -------------
# One fat key holds SKEWJOIN_FAT of the build rows, so static hash
# partitioning lands ~90% of the build on ONE channel — past the grace-join
# spill cliff (SPILL_JOIN_BUILD_ROWS, lowered for the bench so SF doesn't
# matter) that channel builds on disk.  The adaptive run's skew trigger
# (planner/adapt.py) salts the fat partition across all channels, keeping
# every build under the cliff and in memory.  The metric is the wall-clock
# ratio static/adaptive; `--check` requires >= SKEWJOIN_MIN_SPEEDUP.
SKEWJOIN_BUILD_ROWS = int(300_000 * max(SF, 0.1))
SKEWJOIN_KEYS = 1_000
SKEWJOIN_FAT = 0.9
SKEWJOIN_SPILL_ROWS = int(SKEWJOIN_BUILD_ROWS * 2 / 3)
# small row groups: the skew trigger can only fire on a batch boundary, so
# finer batches mean an earlier re-partition (less pre-trigger residue on
# the fat channel) and a static run that pays the spill tier per batch
SKEWJOIN_ROW_GROUP = 1 << 13
# grace-join fanout for the scenario: the spill tier sized for a genuinely
# memory-tight box (64 partitions of ~4.5k rows each at SF 1), not the
# roomy default — this is what the adaptive run gets to skip entirely
SKEWJOIN_SPILL_FANOUT = 64


def _skewjoin_paths():
    """Seeded zipfian-ish skew pair, cached beside the TPC-H parquet."""
    probe_p = os.path.join(CACHE, f"skewprobe_sf{SF}.parquet")
    build_p = os.path.join(
        CACHE, f"skewbuild_sf{SF}_rg{SKEWJOIN_ROW_GROUP}.parquet")
    if not (os.path.exists(probe_p) and os.path.exists(build_p)):
        import numpy as np
        import pyarrow as pa
        import pyarrow.parquet as pq

        r = np.random.default_rng(20260807)
        keys = r.integers(1, SKEWJOIN_KEYS,
                          SKEWJOIN_BUILD_ROWS).astype(np.int64)
        keys[r.random(SKEWJOIN_BUILD_ROWS) < SKEWJOIN_FAT] = 0
        pq.write_table(pa.table({
            "k": keys,
            "v": r.integers(0, 1000, SKEWJOIN_BUILD_ROWS).astype(np.int64),
        }), build_p, row_group_size=SKEWJOIN_ROW_GROUP)
        pq.write_table(pa.table({
            "pk": np.arange(SKEWJOIN_KEYS, dtype=np.int64),
            "g": np.arange(SKEWJOIN_KEYS, dtype=np.int64) % 50,
        }), probe_p)
    return {"probe": probe_p, "build": build_p}


def build_skewjoin(paths, ctx=None):
    ctx = ctx or _ctx()
    probe = ctx.read_parquet(paths["probe"])
    build = ctx.read_parquet(paths["build"])  # right side = build = skewed
    return (probe.join(build, left_on="pk", right_on="k")
            .groupby("g").agg_sql("sum(v) as sv, count(*) as n"))


def run_skewjoin(paths):
    qry = build_skewjoin(paths)
    t0 = time.time()
    df = qry.collect()
    dt = time.time() - t0
    assert 0 < len(df) <= 50, df
    return dt


def _quantile(xs, q):
    xs = sorted(xs)
    if not xs:
        return None
    i = min(len(xs) - 1, int(round(q * (len(xs) - 1))))
    return xs[i]


def measure_service(paths, smoke=False):
    """``bench.py --service``: submit the TPC-H queries concurrently through
    a persistent QueryService (2- and 4-way) and report aggregate throughput
    plus per-query p50/p95 latency next to the serial numbers.

    N-way = N concurrent client streams, each submitting q1, q3, q5 (the
    TPC-H throughput-test shape); every stream's queries run on ONE shared
    worker pool with warm scan/compile caches.  The line of record compares
    the N-way wall clock against the same N passes run serially back-to-back
    on the equally-warm one-shot path."""
    from quokka_tpu.service import QueryService

    ways_list = [2] if smoke else [2, 4]
    qnames = list(BUILDERS)
    # warm pass (compiles every query shape + fills the scan cache), then
    # the timed serial pass the concurrent walls compare against
    for name in qnames:
        QUERIES[name](paths)
    serial_seconds = {name: QUERIES[name](paths) for name in qnames}
    serial_pass_s = sum(serial_seconds.values())
    lines = []
    speedups = []
    for ways in ways_list:
        # queued submissions legitimately wait ~a full round of query
        # runtime behind max_concurrent: give admission the same patience
        # as the measurement itself, or slow hosts die on AdmissionTimeout
        svc = QueryService(pool_size=ways, max_concurrent=ways,
                           inflight_per_query=2,
                           admit_timeout=float(MEASURE_TIMEOUT),
                           query_timeout=float(MEASURE_TIMEOUT))
        try:
            t0 = time.time()
            handles = []
            for _stream in range(ways):
                for name in qnames:
                    stream = BUILDERS[name](paths)
                    handles.append((name, svc.submit(stream)))
            per_query = {}
            for name, h in handles:
                ds = h.result(timeout=MEASURE_TIMEOUT)
                if smoke and ds.to_arrow() is None:
                    raise RuntimeError(
                        f"service smoke: {name} returned an empty result")
                t = h.timings()
                t["latency"] = h.latency_stats()  # per-task p50/p95
                per_query.setdefault(name, []).append(t)
            wall = time.time() - t0
        finally:
            svc.shutdown()
        n_queries = ways * len(qnames)
        serial_wall = ways * serial_pass_s
        speedup = serial_wall / wall if wall > 0 else 0.0
        speedups.append(speedup)
        lat_detail = {}
        for name, ts in per_query.items():
            runs = [t["run_s"] for t in ts if t["run_s"] is not None]
            totals = [
                t["finished_at"] - t["submitted_at"] for t in ts
                if t["finished_at"] is not None
            ]
            task_p50 = [t["latency"]["p50"] for t in ts
                        if t.get("latency") and t["latency"]["p50"]]
            task_p95 = [t["latency"]["p95"] for t in ts
                        if t.get("latency") and t["latency"]["p95"]]
            lat_detail[name] = {
                "serial_s": round(serial_seconds[name], 4),
                "run_p50_s": round(_quantile(runs, 0.5), 4),
                "run_p95_s": round(_quantile(runs, 0.95), 4),
                "total_p50_s": round(_quantile(totals, 0.5), 4),
                "total_p95_s": round(_quantile(totals, 0.95), 4),
                # per-TASK dispatch-latency quantiles from the typed
                # per-query histograms (QueryService.stats() shape)
                "task_p50_s": round(_quantile(task_p50, 0.5), 6)
                if task_p50 else None,
                "task_p95_s": round(_quantile(task_p95, 0.5), 6)
                if task_p95 else None,
            }
            sys.stderr.write(
                f"bench --service [{ways}-way] {name}: "
                f"task p50={lat_detail[name]['task_p50_s']}s "
                f"p95={lat_detail[name]['task_p95_s']}s over "
                f"{sum(t['latency']['count'] for t in ts if t.get('latency'))}"
                " dispatches\n")
        lines.append({
            "metric": f"service_{ways}way_aggregate_speedup",
            "value": round(speedup, 4),
            "unit": "x",
            "vs_baseline": round(speedup, 4),
            "detail": {
                "sf": SF,
                "ways": ways,
                "cpus": os.cpu_count(),  # 1-core hosts cannot beat serial
                "queries": n_queries,
                "wall_s": round(wall, 4),
                "serial_back_to_back_s": round(serial_wall, 4),
                "aggregate_qps": round(n_queries / wall, 4),
                "serial_qps": round(n_queries / serial_wall, 4),
                "per_query": lat_detail,
            },
        })
    # mixed-load line: the same 2-way workload with one CANCELLED and one
    # DEADLINE-EXCEEDED query in the mix.  Both casualties carry oversized
    # working-set declarations so they wait QUEUED behind the running
    # normals — the cancel and the deadline land deterministically at the
    # admission queue, never racing a finish — and first-class cancellation
    # must cost the surviving queries nothing: the line of record is the
    # mixed-run aggregate qps over the plain 2-way run's.
    from quokka_tpu.service import DeadlineExceeded, QueryCancelled

    ways = ways_list[0]
    # the byte budget is what pins the casualties: 1 PiB declarations can
    # never admit under 4 GiB, no matter how fast the normals drain
    svc = QueryService(pool_size=ways, max_concurrent=ways,
                       inflight_per_query=2, mem_budget=4 << 30,
                       admit_timeout=float(MEASURE_TIMEOUT),
                       query_timeout=float(MEASURE_TIMEOUT))
    try:
        t0 = time.time()
        handles = []
        for _stream in range(ways):
            for name in qnames:
                handles.append((name, svc.submit(BUILDERS[name](paths))))
        victim = svc.submit(BUILDERS[qnames[0]](paths),
                            working_set_bytes=1 << 50)
        # the deadline must expire while the normals still hold the pool
        # (the queued-reaper path) — generous values race a warm cache's
        # fast drain, after which an oversized query may legally run alone
        expired = svc.submit(BUILDERS[qnames[0]](paths),
                             working_set_bytes=1 << 50, deadline_s=0.02)
        victim.cancel(wait=False)
        for name, h in handles:
            h.result(timeout=MEASURE_TIMEOUT)
        wall = time.time() - t0
        try:
            victim.result(timeout=60)
            raise RuntimeError("bench --service mixed load: the cancelled "
                               "query returned a result")
        except QueryCancelled:
            pass
        try:
            expired.result(timeout=60)
            raise RuntimeError("bench --service mixed load: the deadline "
                               "query returned a result")
        except DeadlineExceeded:
            pass
        leaked = svc.admission.stats()["used_bytes"]
        if leaked:
            raise RuntimeError(
                f"bench --service mixed load: {leaked} admission bytes "
                "still held after cancel/deadline/finish")
    finally:
        svc.shutdown()
    n_queries = ways * len(qnames)
    mixed_qps = n_queries / wall if wall > 0 else 0.0
    plain_qps = lines[0]["detail"]["aggregate_qps"]
    lines.append({
        "metric": "service_mixed_load_throughput_ratio",
        "value": round(mixed_qps / plain_qps if plain_qps else 0.0, 4),
        "unit": "x",
        "vs_baseline": round(mixed_qps / plain_qps if plain_qps else 0.0, 4),
        "detail": {
            "sf": SF,
            "ways": ways,
            "queries": n_queries,
            "wall_s": round(wall, 4),
            "mixed_qps": round(mixed_qps, 4),
            "plain_qps": plain_qps,
            "cancelled": 1,
            "deadline_exceeded": 1,
            "admission_bytes_leaked": 0,
        },
    })
    for ln in lines:
        print(json.dumps(ln))
    geomean = math.exp(sum(math.log(max(s, 1e-9)) for s in speedups)
                       / len(speedups))
    print(json.dumps({
        "metric": "service_aggregate_speedup_geomean",
        "value": round(geomean, 4),
        "unit": "x",
        "vs_baseline": round(geomean, 4),
        "detail": {"sf": SF, "ways": ways_list,
                   "serial_seconds": {k: round(v, 4)
                                      for k, v in serial_seconds.items()}},
    }))
    sys.stdout.flush()
    return geomean

# span-name prefix -> breakdown bucket (obs/spans.py names).  push./spill.
# are TRANSFER (partition push bookkeeping + HBQ spill d2h/write), matching
# the critical-path profiler's attribution (obs/critpath.py) so the two
# reports agree on where exchange time goes.
_BUCKET_PREFIXES = (
    (("reader.", "prefetch"), "read_s"),
    (("bridge.", "emit.", "push.", "spill.", "count_valid"), "transfer_s"),
    (("exec.", "done.", "source."), "compute_s"),
)


def _span_breakdown(span_stats):
    """Collapse a spans.stats() snapshot into read/transfer/compute buckets
    (compile time is taken from compilestats deltas, not spans)."""
    buckets = {"read_s": 0.0, "transfer_s": 0.0, "compute_s": 0.0,
               "other_s": 0.0}
    for name, st in span_stats.items():
        for prefixes, bucket in _BUCKET_PREFIXES:
            if name.startswith(prefixes):
                buckets[bucket] += st["total_s"]
                break
        else:
            buckets["other_s"] += st["total_s"]
    return {k: round(v, 4) for k, v in buckets.items()}


def bench_out_dir() -> str:
    """Per-run bench artifacts (bench_obs.json, timed multichip JSON) land
    under ONE gitignored output dir instead of littering the repo root —
    override the dir with QUOKKA_BENCH_OUT."""
    d = os.environ.get("QUOKKA_BENCH_OUT", "bench_out")
    os.makedirs(d, exist_ok=True)
    return d


def _operators_detail():
    """EXPLAIN ANALYZE actuals of the most recently finished query — the
    opstats ledger stashes its final snapshot at query GC, so reading it
    right after a timed run attributes to that run.  None when the ledger
    saw nothing (itself a regression on join/asof queries: `--check`)."""
    try:
        from quokka_tpu.obs import explain as obs_explain
        from quokka_tpu.obs import opstats as obs_opstats

        return obs_explain.operators_detail(
            obs_opstats.OPSTATS.last_finished())
    except Exception as e:  # noqa: BLE001 — stats must not kill the bench
        sys.stderr.write(f"bench: operators detail unavailable: {e!r}\n")
        return None


def _efficiency_detail():
    """Device-efficiency digest of the most recently finished query
    (obs/devprof.py figures attached to the opstats snapshot at query GC):
    calibrated peaks + per-operator achieved FLOP/s, bandwidth and
    roofline %%.  None when the plane saw nothing."""
    try:
        from quokka_tpu.obs import explain as obs_explain
        from quokka_tpu.obs import opstats as obs_opstats

        return obs_explain.efficiency_detail(
            obs_opstats.OPSTATS.last_finished())
    except Exception as e:  # noqa: BLE001 — stats must not kill the bench
        sys.stderr.write(f"bench: efficiency detail unavailable: {e!r}\n")
        return None


def _progress_detail():
    """Final progress snapshot of the most recently finished query (the
    health plane stashes it at query GC, same discipline as the opstats
    detail): fraction/basis/elapsed prove the estimator tracked the run.
    None when the tracker saw nothing."""
    try:
        from quokka_tpu.obs import progress as obs_progress

        snap = obs_progress.TRACKER.last_finished()
        if not snap:
            return None
        return {k: snap.get(k) for k in
                ("fraction", "basis", "elapsed_s", "source_bytes_done",
                 "source_bytes_total", "profiled_ops")}
    except Exception as e:  # noqa: BLE001 — stats must not kill the bench
        sys.stderr.write(f"bench: progress detail unavailable: {e!r}\n")
        return None


def _fused_stages(operators):
    """How many whole-stage-fused operators actually dispatched in the last
    timed run (detail.operators rows whose op is a FusedStage,
    ops/stagefuse.py).  The join queries must report >= 1: `--check` treats
    a fresh join line without the field — or with zero fused stages while
    fusion is on by default — as the fusion win silently evaporating."""
    if not operators:
        return 0
    return sum(1 for o in operators.get("operators") or ()
               if str(o.get("op", "")).startswith("FusedStage")
               and o.get("dispatches", 0) > 0)


def _write_obs_summary(obs_per_query):
    """Per-query span/counter breakdown JSON next to the timing output
    (BENCH_*.json gains compile-vs-compute-vs-transfer visibility)."""
    from quokka_tpu import obs

    path = os.environ.get("QUOKKA_BENCH_OBS") or os.path.join(
        bench_out_dir(), "bench_obs.json")
    try:
        with open(path, "w", encoding="utf-8") as f:
            json.dump({"per_query": obs_per_query,
                       "counters": obs.REGISTRY.snapshot()}, f, indent=2)
        sys.stderr.write(f"bench: per-query span/counter summary: {path}\n")
    except OSError as e:
        sys.stderr.write(f"bench: could not write obs summary {path}: {e}\n")


# `--check` floor for the skewjoin line: the adaptive run must beat the
# statically-skewed run by at least this factor (the tentpole's headline)
SKEWJOIN_MIN_SPEEDUP = 2.0


def measure_skewjoin(platform):
    """The skewjoin_adaptive_speedup line: the same zipfian join timed with
    runtime adaptation on (default) vs off (``QK_ADAPT=0``).

    Both variants plan COLD with cardprofile persistence OFF
    (QK_CARDPROFILE_DIR=""), so every run's plan is identical except for
    the adaptation mark — otherwise the first run's measured figures would
    shrink the tiny-output join to one channel and erase the very skew the
    trigger exists to fix.  The grace-join spill cliff is lowered to
    SKEWJOIN_SPILL_ROWS so the static run's fat channel builds on disk
    while the adapted run's salted channels all stay in memory.  One
    warmup run per variant pays the compiles; the value is best-of-2
    static seconds over best-of-2 adaptive seconds."""
    from quokka_tpu import config as qk_config

    env_overrides = {
        "QK_CARDPROFILE_DIR": "",
        # the skewed side must go through a hash EXCHANGE for the runtime
        # trigger to have an edge to re-partition: pin broadcast off
        "QK_BROADCAST_BYTES": "1",
        "QK_SKEW_RATIO": "1.5",
        "QK_ADAPT_MIN_ROWS": "20000",
    }
    saved_env = {k: os.environ.get(k) for k in (*env_overrides, "QK_ADAPT")}
    os.environ.update(env_overrides)
    saved_spill = qk_config.SPILL_JOIN_BUILD_ROWS
    saved_fanout = qk_config.SPILL_JOIN_FANOUT
    qk_config.SPILL_JOIN_BUILD_ROWS = SKEWJOIN_SPILL_ROWS
    qk_config.SPILL_JOIN_FANOUT = SKEWJOIN_SPILL_FANOUT
    try:
        paths = _skewjoin_paths()
        os.environ["QK_ADAPT"] = "0"
        run_skewjoin(paths)  # compile warm-up (static plan)
        static = sorted(run_skewjoin(paths) for _ in range(2))
        os.environ.pop("QK_ADAPT", None)
        run_skewjoin(paths)  # warm-up (adaptive: same kernels + salt/replicate)
        adaptive = sorted(run_skewjoin(paths) for _ in range(2))
        ops_detail = _operators_detail()
        planner = (ops_detail or {}).get("planner") or []
        adapted = any(d.get("kind") == "adapt_runtime" for d in planner)
        speedup = static[0] / adaptive[0]
        sys.stderr.write(
            f"bench: skewjoin static {static[0]:.3f}s adaptive "
            f"{adaptive[0]:.3f}s ({speedup:.2f}x, adapted={adapted})\n")
        return {
            "metric": "skewjoin_adaptive_speedup",
            "value": round(speedup, 4),
            "unit": "x",
            # normalized so 1.0 == exactly the required 2x floor
            "vs_baseline": round(speedup / SKEWJOIN_MIN_SPEEDUP, 4),
            "detail": {
                "sf": SF, "platform": platform,
                "build_rows": SKEWJOIN_BUILD_ROWS,
                "fat_fraction": SKEWJOIN_FAT,
                "spill_join_rows": SKEWJOIN_SPILL_ROWS,
                "spill_join_fanout": SKEWJOIN_SPILL_FANOUT,
                "seconds_static": [round(x, 4) for x in static],
                "seconds_adaptive": [round(x, 4) for x in adaptive],
                # proof the adaptive run actually re-partitioned mid-query
                # (`--check` fails a fresh line where the trigger slept)
                "adapted": adapted,
                "operators": ops_detail,
            },
        }
    finally:
        qk_config.SPILL_JOIN_BUILD_ROWS = saved_spill
        qk_config.SPILL_JOIN_FANOUT = saved_fanout
        for k, v in saved_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def measure(paths):
    """The full measurement (runs inside the supervised child).  Emits one
    JSON line per query + the final summary line on fd 1 and exits 0."""
    import jax

    platform = jax.default_backend()
    nbytes = os.path.getsize(paths["lineitem"])
    per_query = {}
    from quokka_tpu.obs import spans as obs_spans
    from quokka_tpu.ops import strategy as kstrategy
    from quokka_tpu.utils import compilestats

    # the kernel-strategy matrix decides which kernels the bench times:
    # calibrate once per backend (persisted under the compile plane's
    # fingerprint) BEFORE the per-query compile snapshots, so the
    # calibration microbench's compiles never count as query warmup.
    # Every benched line then records the strategies that actually RAN
    # (detail.strategy), which `bench.py --check` validates against the
    # bench platform — the permanent fix for measuring a path the target
    # backend never runs (VERDICT r5 #2).
    kstrategy.ensure_calibrated()
    # device-profile peaks (obs/devprof.py): calibrate alongside the kernel
    # strategy matrix — same fingerprint discipline, same pre-query timing
    # so the microbench compiles never count as query warmup.  Each benched
    # line then carries detail.efficiency (achieved vs roofline).
    from quokka_tpu.obs import devprof as qk_devprof

    qk_devprof.ensure_calibrated()
    strategy_meta = {"choices": kstrategy.choices(),
                     "sources": kstrategy.sources()}
    sys.stderr.write(f"bench: kernel strategies {strategy_meta['choices']} "
                     f"(sources {strategy_meta['sources']})\n")

    # span aggregation ON regardless of QUOKKA_TRACE: the per-query
    # breakdown JSON is part of the bench contract; QUOKKA_TRACE=1 only
    # decides whether the human-readable summary prints too (read through
    # spans.enabled() — the one owner of the env truthiness rule)
    trace_print = obs_spans.enabled()
    obs_spans.set_enabled(True)
    obs_per_query = {}
    from quokka_tpu import obs as qk_obs

    def _shuffle_snap():
        snap = qk_obs.REGISTRY.snapshot()
        return {k: snap.get(k, 0) for k in
                ("shuffle.bytes", "shuffle.host_syncs", "shuffle.spill_bytes")}

    from quokka_tpu.analysis import planck as qk_planck
    from quokka_tpu.obs import memplane

    for qname, fn in QUERIES.items():
        ref = REF_SECONDS_SF100_4W[qname] * 4.0 / 100.0 * SF
        obs_spans.reset()
        kstrategy.reset_used()
        c0 = compilestats.snapshot()
        sh0 = _shuffle_snap()
        pv0 = dict(qk_planck.VERIFY_STATS)
        # memory plane: peak resets to current live before the query, so
        # detail.memory reports THIS query's high-water mark, not the
        # session's
        memplane.LEDGER.reset_peak()
        warm = fn(paths)  # compiles the kernel set for this query shape
        extra = {}
        if qname == "q1":
            # cold = compile warm but scan (buffer-pool) cache empty: pays
            # parquet decode + host encode + h2d transfer every batch.
            # (Runs before the compile snapshot so any shape first seen on
            # the cold path counts as warmup, not as timed-run churn.)
            from quokka_tpu.runtime import scancache

            scancache.clear()
            cold = fn(paths)
            extra = {
                "q1_seconds_cold_scan": round(cold, 4),
                "cold_scan_gbps": round(nbytes / cold / 1e9, 4),
                "cold_vs_baseline": round(
                    nbytes / cold / 1e9 / BASELINE_GBPS_PER_WORKER, 4
                ),
            }
        c1 = compilestats.snapshot()
        # two span windows so the buckets reconcile with their neighbors:
        # "warmup" pairs with warmup_seconds/compile_seconds_warmup,
        # "timed_runs" sums over the 3 runs whose best is `seconds`
        spans_warmup = obs_spans.stats()
        if trace_print:
            sys.stderr.write(f"[spans] {qname} warmup\n"
                             + obs_spans.summary() + "\n")
        obs_spans.reset()
        # critical-path profile of the LAST timed run: the DAG rebuilt from
        # the flight recorder, wall time attributed into compile/scan/
        # transfer/compute/queue/stall buckets (obs/critpath.py)
        from quokka_tpu.obs import critpath as obs_critpath

        sh1 = _shuffle_snap()
        times = [fn(paths) for _ in range(2)]
        with obs_critpath.profile() as _prof:
            times.append(fn(paths))
        crit = None
        if _prof.result is not None:
            crit = _prof.result.to_json()
            crit["measured_wall_s"] = round(times[-1], 4)
            # the full segment list lives in bench_obs.json; the stdout
            # line of record carries the bucket attribution only
            crit_line = {k: v for k, v in crit.items() if k != "path"}
            if trace_print:
                sys.stderr.write(_prof.result.render() + "\n")
        else:
            crit_line = None
        times = sorted(times)
        c2 = compilestats.snapshot()
        sh2 = _shuffle_snap()
        # shuffle volume of the 3 timed runs (counter deltas): bytes through
        # fan-out>1 exchanges, blocking host readbacks on the partition
        # path, and spilled bytes (0 without fault tolerance)
        shuffle_detail = {
            "warmup": {k.split(".", 1)[1]: int(sh1[k] - sh0[k]) for k in sh0},
            "per_timed_run": {k.split(".", 1)[1]: int((sh2[k] - sh1[k]) / 3)
                              for k in sh0},
        }
        t = times[0]
        speedup = ref / t
        spans_timed = obs_spans.stats()
        breakdown = {
            "warmup": {
                **_span_breakdown(spans_warmup),
                "compile_s": round(c1["backend_compile_seconds"]
                                   - c0["backend_compile_seconds"], 3),
            },
            "timed_runs": {
                **_span_breakdown(spans_timed),
                "runs": 3,
                "compile_s": round(c2["backend_compile_seconds"]
                                   - c1["backend_compile_seconds"], 3),
            },
        }
        obs_per_query[qname] = {"spans_warmup": spans_warmup,
                                "spans_timed": spans_timed,
                                "breakdown": breakdown,
                                "critpath": crit}
        if trace_print:
            sys.stderr.write(f"[spans] {qname} timed runs (3)\n"
                             + obs_spans.summary() + "\n")
        ops_detail = _operators_detail()
        pv_plans = qk_planck.VERIFY_STATS["plans"] - pv0["plans"]
        pv_ms = qk_planck.VERIFY_STATS["ms_total"] - pv0["ms_total"]
        per_query[qname] = {
            "seconds": round(t, 4),
            "seconds_all": [round(x, 4) for x in times],
            "warmup_seconds": round(warm, 4),
            "ref_seconds_scaled": round(ref, 4),
            "speedup_vs_ref_per_chip": round(speedup, 4),
            # kernel-reuse proof: warmup pays the real compiles and/or
            # persistent-cache loads, the timed runs must not add any
            "real_compiles_warmup": c1["real_compiles"] - c0["real_compiles"],
            "real_compiles_timed_runs": c2["real_compiles"] - c1["real_compiles"],
            "compile_seconds_warmup": round(
                c1["backend_compile_seconds"] - c0["backend_compile_seconds"], 3
            ),
            "cache_hits_warmup": c1["cache_hits"] - c0["cache_hits"],
            "breakdown": breakdown,
            "shuffle": shuffle_detail,
            # memory-ledger footprint across warmup + timed runs: device
            # high-water mark and spill-bytes delta (obs/memplane.py);
            # `--check` gates peak_bytes growth like warmup_seconds
            "memory": {
                "peak_bytes": int(memplane.LEDGER.peak_bytes()),
                "spill_bytes": int(sh2["shuffle.spill_bytes"]
                                   - sh0["shuffle.spill_bytes"]),
            },
            # the kernel family each strategy-dispatched operator actually
            # executed during this query (ops/strategy.note_used)
            "strategy": kstrategy.used_snapshot(),
            "critpath": crit_line,
            # EXPLAIN ANALYZE actuals of the last timed run (obs/opstats.py
            # snapshot stashed at query GC): per-operator rows/selectivity/
            # time share + the per-exchange-edge skew report.  `--check`
            # treats a missing block on join/asof queries as a regression.
            "operators": ops_detail,
            # proof the whole-stage-fused plan is what was measured: count
            # of FusedStage operators that dispatched (`--check` gates the
            # join lines on this being >= 1)
            "fused_stages": _fused_stages(ops_detail),
            # device-efficiency digest of the last timed run
            # (obs/devprof.py): peaks + per-operator roofline %.  `--check`
            # treats a missing block on join/asof lines as a regression.
            "efficiency": _efficiency_detail(),
            # health plane: the progress estimator's final snapshot for the
            # last timed run (obs/progress.py, stashed at query GC)
            "progress": _progress_detail(),
            # plan-invariant verifier cost (QK021-QK024, plan-time only):
            # per-plan average must stay <= 5 ms
            "plan_verify": {
                "plans": pv_plans,
                "ms_total": round(pv_ms, 3),
                "ms_per_plan": round(pv_ms / pv_plans, 3) if pv_plans else 0.0,
            },
            **extra,
        }
        # QK_SANITIZE=1: the recompile sentinel fails the run outright when
        # the timed runs compiled anything — a warmed query shape must reuse
        # its executables (analysis/sanitize.py)
        from quokka_tpu.analysis import sanitize

        sanitize.check_no_recompiles(c1, c2, context=f"{qname} timed runs")
        if qname == "q1":
            gbps = nbytes / t / 1e9
            print(json.dumps({
                "metric": "tpch_q1_scan_gbps_per_chip",
                "value": round(gbps, 4),
                "unit": "GB/s",
                "vs_baseline": round(gbps / BASELINE_GBPS_PER_WORKER, 4),
                "detail": {"sf": SF, "parquet_bytes": nbytes,
                           "platform": platform, **per_query[qname]},
            }))
        else:
            print(json.dumps({
                "metric": f"tpch_{qname}_speedup_vs_ref_per_chip",
                "value": round(speedup, 4),
                "unit": "x",
                "vs_baseline": round(speedup, 4),
                "detail": {"sf": SF, "platform": platform,
                           **per_query[qname]},
            }))
        sys.stdout.flush()
    # tick backtest: rows/s per chip vs the reference's per-worker rate.
    # The section carries its OWN alarm so an asof compile overrun/wedge
    # skips this one line instead of blowing the child's overall timeout
    # and discarding the already-printed TPC-H lines of record.
    import signal

    def _asof_alarm(sig, frm):
        raise TimeoutError("asof benchmark section timed out")

    old_handler = signal.signal(signal.SIGALRM, _asof_alarm)
    signal.alarm(int(os.environ.get("QUOKKA_BENCH_ASOF_TIMEOUT", "600")))
    try:
        obs_spans.reset()
        kstrategy.reset_used()
        run_asof(paths)  # compile warm-up
        asof_times = sorted(run_asof(paths) for _ in range(3))
        asof_rows = ASOF_TRADES + ASOF_QUOTES
        asof_rps = asof_rows / asof_times[0]
        asof_speedup = asof_rps / REF_ASOF_ROWS_PER_S_PER_WORKER
        asof_ops = _operators_detail()
        print(json.dumps({
            "metric": "tick_asof_rows_per_s_per_chip",
            "value": round(asof_rps),
            "unit": "rows/s",
            "vs_baseline": round(asof_speedup, 4),
            "detail": {
                "sf": SF, "platform": platform,
                "trades": ASOF_TRADES, "quotes": ASOF_QUOTES,
                "seconds_all": [round(x, 4) for x in asof_times],
                "ref_rows_per_s_per_worker": round(REF_ASOF_ROWS_PER_S_PER_WORKER),
                "strategy": kstrategy.used_snapshot(),
                "operators": asof_ops,
                "fused_stages": _fused_stages(asof_ops),
                "efficiency": _efficiency_detail(),
            },
        }))
        sys.stdout.flush()
        asof_spans = obs_spans.stats()
        obs_per_query["asof"] = {
            "spans": asof_spans,
            # one window here: warmup + 3 timed runs (the asof line reports
            # seconds_all, not a single best-run pairing)
            "breakdown": {**_span_breakdown(asof_spans), "runs": 4},
        }
        if trace_print:
            sys.stderr.write("[spans] asof (warmup + 3 timed runs)\n"
                             + obs_spans.summary() + "\n")
    except Exception as e:  # noqa: BLE001 — the TPC-H lines must survive
        sys.stderr.write(f"bench: asof section skipped: {e}\n")
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old_handler)
    # skewjoin: adaptive-vs-static under zipfian build skew.  Own alarm so
    # a wedge here skips one line, not the already-printed TPC-H lines.
    def _skew_alarm(sig, frm):
        raise TimeoutError("skewjoin benchmark section timed out")

    old_handler = signal.signal(signal.SIGALRM, _skew_alarm)
    signal.alarm(int(os.environ.get("QUOKKA_BENCH_SKEW_TIMEOUT", "600")))
    try:
        print(json.dumps(measure_skewjoin(platform)))
        sys.stdout.flush()
    except Exception as e:  # noqa: BLE001 — the TPC-H lines must survive
        sys.stderr.write(f"bench: skewjoin section skipped: {e}\n")
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old_handler)
    _write_obs_summary(obs_per_query)
    geomean = math.exp(
        sum(math.log(v["speedup_vs_ref_per_chip"]) for v in per_query.values())
        / len(per_query)
    )
    print(json.dumps({
        "metric": "tpch_q135_speedup_geomean_per_chip",
        "value": round(geomean, 4),
        "unit": "x",
        "vs_baseline": round(geomean, 4),
        "detail": {
            "sf": SF,
            "queries": per_query,
            "ref_seconds_sf100_4workers": REF_SECONDS_SF100_4W,
            "platform": platform,
            "tpu_fallback_to_cpu": platform == "cpu",
            "strategy_matrix": strategy_meta,
        },
    }))
    # roofline-efficiency geomean across every attributed operator of the
    # benched queries (obs/devprof.py): the one number `--trend` tracks for
    # "is the engine getting more or less out of the device per round"
    effs = [r["efficiency"] for q in per_query.values()
            for r in ((q.get("efficiency") or {}).get("operators") or ())
            if r.get("efficiency")]
    if effs:
        eff_geo = math.exp(sum(math.log(e) for e in effs) / len(effs))
        print(json.dumps({
            "metric": "devprof_efficiency_geomean",
            "value": round(eff_geo, 6),
            "unit": "frac",
            "vs_baseline": round(eff_geo, 6),
            "detail": {
                "operators": len(effs),
                "platform": platform,
                "peaks": next((q["efficiency"]["peaks"]
                               for q in per_query.values()
                               if q.get("efficiency")), None),
            },
        }))


def probe_tpu(attempts: int = 2, timeout: int = 150, backoff: int = 20) -> bool:
    """Check the TPU backend from a SUBPROCESS so a wedged tunnel (which hangs
    jax.devices() indefinitely) can't hang the bench itself.  Bounded retries
    with backoff; False means the tunnel is down after all attempts."""
    probe = (
        "import jax, jax.numpy as jnp;"
        "d = jax.devices();"
        "(jnp.ones((64, 64)) @ jnp.ones((64, 64))).block_until_ready();"
        "print('ok', d[0].platform)"
    )
    for i in range(attempts):
        try:
            r = subprocess.run(
                [sys.executable, "-c", probe],
                timeout=timeout, capture_output=True, text=True,
            )
            if r.returncode == 0 and "ok" in r.stdout:
                platform = r.stdout.strip().split()[-1].lower()
                if platform not in ("cpu",):
                    return True
                # JAX silently picked CPU (plugin missing): that is NOT a TPU
                sys.stderr.write(
                    f"bench: probe initialized platform {platform!r}, not TPU\n"
                )
                return False
            sys.stderr.write(
                f"bench: TPU probe {i + 1}/{attempts} failed rc={r.returncode}: "
                f"{(r.stderr or r.stdout)[-200:]}\n"
            )
        except subprocess.TimeoutExpired:
            sys.stderr.write(f"bench: TPU probe {i + 1}/{attempts} timed out\n")
        if i < attempts - 1:
            time.sleep(backoff)
    return False


def _run_child(platform: str, timeout: int):
    """Run measure() in a child; returns the JSON lines or None on wedge."""
    env = dict(os.environ)
    if platform == "cpu":
        env["QUOKKA_BENCH_FORCE_CPU"] = "1"
    try:
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--measure"],
            timeout=timeout, capture_output=True, text=True, env=env,
        )
    except subprocess.TimeoutExpired:
        sys.stderr.write(
            f"bench: measurement child exceeded {timeout}s (wedged tunnel?)\n"
        )
        return None
    if r.returncode != 0:
        sys.stderr.write(f"bench: measurement child rc={r.returncode}:\n"
                         f"{r.stderr[-2000:]}\n")
        return None
    if r.stderr:
        # the child's stderr carries the QUOKKA_TRACE span summaries and the
        # obs-summary path; forward it (stdout stays machine-parseable)
        sys.stderr.write(r.stderr[-8000:])
    lines = [
        ln.strip() for ln in r.stdout.strip().splitlines()
        if ln.strip().startswith("{")
    ]
    if lines:
        return lines
    sys.stderr.write(f"bench: child produced no JSON: {r.stdout[-500:]}\n")
    return None


# ---------------------------------------------------------------------------
# --check: perf-regression gate
# ---------------------------------------------------------------------------
# Per-metric relative regression thresholds on the normalized vs_baseline
# ratios (all bench metrics are higher-is-better).  Defaults are sized for
# the shared-CI noise floor observed across BENCH_r01..r05; the geomean is
# tighter because noise averages out across queries.
CHECK_THRESHOLDS = {
    "tpch_q135_speedup_geomean_per_chip": 0.15,
    "tpch_q1_scan_gbps_per_chip": 0.30,
    "tick_asof_rows_per_s_per_chip": 0.30,
    "service_aggregate_speedup_geomean": 0.30,
    "service_mixed_load_throughput_ratio": 0.30,
    # multichip scaling efficiency: forced-host runs share one core pool,
    # so the ratio is noisier than the single-device walls
    "multichip_scaling_efficiency_geomean": 0.40,
}
CHECK_DEFAULT_THRESHOLD = 0.25

# Benched lines that MUST record the kernel strategy that actually ran
# (detail.strategy, from ops/strategy.note_used): the join queries and the
# tick asof are exactly where a platform-gated kernel once made the bench
# measure a path the target backend never runs.
STRATEGY_REQUIRED_METRICS = (
    "tpch_q3_speedup_vs_ref_per_chip",
    "tpch_q5_speedup_vs_ref_per_chip",
    "tick_asof_rows_per_s_per_chip",
)


def _iter_strategy_details(metric, d):
    """(heading, platform, strategy_dict) for a metric line and any nested
    per-query details (the geomean wrapper)."""
    detail = d.get("detail") or {}
    plat = detail.get("platform")
    if detail.get("strategy"):
        yield metric, plat, detail["strategy"]
    for qname, qd in sorted((detail.get("queries") or {}).items()):
        if isinstance(qd, dict) and qd.get("strategy"):
            yield f"{metric}:{qname}", plat, qd["strategy"]


def check_strategy_honesty(cur, require):
    """Bench-honesty gate rows: every recorded (operator -> kernel choice)
    must be RUNNABLE on the recorded bench platform
    (ops/strategy.invalid_for_platform), and — when ``require`` (fresh runs,
    whose emitter we control) — the join/asof lines must record strategies
    at all.  Returns (rows, violations): a violation exits --check nonzero,
    closing VERDICT r5 finding #2 permanently."""
    from quokka_tpu.ops import strategy as kstrategy

    rows, bad = [], []
    seen_with_strategy = set()
    for metric, d in sorted(cur.items()):
        for heading, plat, strat in _iter_strategy_details(metric, d):
            seen_with_strategy.add(metric)
            for op, ran in sorted(strat.items()):
                name = f"strategy[{heading}].{op}={ran}"
                why = kstrategy.invalid_for_platform(plat or "cpu", op, ran)
                if why:
                    rows.append((name, "GATED-OFF", why))
                    bad.append(name)
                else:
                    rows.append((name, "ok", f"runnable on {plat or 'cpu'}"))
    if require:
        for metric in STRATEGY_REQUIRED_METRICS:
            if metric in cur and metric not in seen_with_strategy:
                name = f"strategy[{metric}]"
                rows.append((name, "MISSING",
                             "benched line records no kernel strategy — "
                             "cannot verify the measured path is the one "
                             "this platform runs"))
                bad.append(name)
    return rows, bad


def check_operators_presence(cur, require):
    """EXPLAIN ANALYZE honesty rows: benched join/asof lines must carry
    the operator-statistics block (``detail.operators`` — per-operator
    rows/time + the skew report) when ``require`` (fresh runs, whose
    emitter we control).  A missing block means the opstats ledger went
    blind on that query — a regression, exactly like a vanished metric.
    Returns (rows, violations)."""
    rows, bad = [], []
    if not require:
        return rows, bad

    def _has_operators(d):
        detail = d.get("detail") or {}
        if detail.get("operators"):
            return True
        return any(isinstance(qd, dict) and qd.get("operators")
                   for qd in (detail.get("queries") or {}).values())

    for metric in STRATEGY_REQUIRED_METRICS:
        if metric not in cur:
            continue
        name = f"operators[{metric}]"
        if _has_operators(cur[metric]):
            ops = (cur[metric].get("detail") or {}).get("operators") or {}
            n = len(ops.get("operators") or []) if isinstance(ops, dict) \
                else 0
            rows.append((name, "ok",
                         f"opstats present ({n} operator(s))"))
        else:
            rows.append((name, "MISSING",
                         "benched line records no detail.operators — the "
                         "EXPLAIN ANALYZE ledger saw nothing for this "
                         "query (opstats regression)"))
            bad.append(name)
    return rows, bad


def check_efficiency_presence(cur, require):
    """Device-efficiency honesty rows: fresh join/asof lines must carry the
    ``detail.efficiency`` block (obs/devprof.py peaks + per-operator
    roofline figures) when ``require`` (fresh runs, whose emitter we
    control — bench --measure calibrates the peaks itself).  A missing
    block means the device-profile plane went blind on that query — same
    presence discipline as strategy/operators.  Returns (rows,
    violations)."""
    rows, bad = [], []
    if not require:
        return rows, bad

    def _efficiency(d):
        detail = d.get("detail") or {}
        if detail.get("efficiency"):
            return detail["efficiency"]
        for qd in (detail.get("queries") or {}).values():
            if isinstance(qd, dict) and qd.get("efficiency"):
                return qd["efficiency"]
        return None

    for metric in STRATEGY_REQUIRED_METRICS:
        if metric not in cur:
            continue
        name = f"efficiency[{metric}]"
        eff = _efficiency(cur[metric])
        if eff:
            n = len(eff.get("operators") or []) if isinstance(eff, dict) \
                else 0
            rows.append((name, "ok",
                         f"devprof present ({n} operator(s))"))
        else:
            rows.append((name, "MISSING",
                         "benched line records no detail.efficiency — the "
                         "device-profile plane saw nothing for this query "
                         "(devprof regression)"))
            bad.append(name)
    return rows, bad


# Benched join lines that MUST prove the whole-stage-fused plan actually
# ran (detail.fused_stages >= 1, counted off the opstats FusedStage rows):
# Q3/Q5 are exactly the linear probe chains ops/stagefuse.py collapses.
FUSION_REQUIRED_METRICS = (
    "tpch_q3_speedup_vs_ref_per_chip",
    "tpch_q5_speedup_vs_ref_per_chip",
)


def check_fused_stages_presence(cur, require):
    """Whole-stage-fusion honesty rows: fresh join lines must carry
    ``detail.fused_stages`` and report at least one fused stage that
    dispatched.  A missing field means the emitter predates stage fusion
    (or the opstats ledger went blind); a zero means the optimizer planned
    no fused chain on a query shaped exactly for one.  Either way the
    fusion win silently evaporated — a regression, same presence
    discipline as strategy/operators.  Returns (rows, violations)."""
    rows, bad = [], []
    if not require:
        return rows, bad
    for metric in FUSION_REQUIRED_METRICS:
        if metric not in cur:
            continue
        name = f"fused_stages[{metric}]"
        detail = cur[metric].get("detail") or {}
        n = detail.get("fused_stages")
        if n is None:
            rows.append((name, "MISSING",
                         "benched join line records no detail.fused_stages "
                         "— cannot verify the whole-stage-fused plan is "
                         "what was measured"))
            bad.append(name)
        elif n < 1:
            rows.append((name, "MISSING",
                         "detail.fused_stages == 0 — no fused stage "
                         "dispatched on a linear join chain (stage fusion "
                         "regressed or was disabled for the bench)"))
            bad.append(name)
        else:
            rows.append((name, "ok", f"{n} fused stage(s) dispatched"))
    return rows, bad


def check_skewjoin_gate(cur, require):
    """Adaptive-planning gate rows: a fresh run must carry the skewjoin
    line, its adaptive run must actually have re-partitioned mid-query
    (detail.adapted), and the speedup must clear SKEWJOIN_MIN_SPEEDUP.
    A missing line, a sleeping trigger, or a sub-floor ratio all mean the
    adaptive win evaporated — same presence discipline as fused_stages.
    Returns (rows, violations)."""
    rows, bad = [], []
    if not require:
        return rows, bad
    metric = "skewjoin_adaptive_speedup"
    name = f"skewjoin[{metric}]"
    d = cur.get(metric)
    if d is None:
        rows.append((name, "MISSING",
                     "fresh run emitted no skewjoin line — the adaptive-vs-"
                     "static benchmark did not run"))
        bad.append(name)
        return rows, bad
    detail = d.get("detail") or {}
    value = float(d.get("value") or 0.0)
    if not detail.get("adapted"):
        rows.append((name, "MISSING",
                     "the adaptive run never fired the skew trigger (no "
                     "adapt_runtime decision) — the measured 'adaptive' "
                     "path was the static one"))
        bad.append(name)
    elif value < SKEWJOIN_MIN_SPEEDUP:
        rows.append((name, "REGRESSED",
                     f"adaptive speedup {value:.2f}x under the required "
                     f"{SKEWJOIN_MIN_SPEEDUP:.0f}x floor "
                     f"(static {detail.get('seconds_static')}, adaptive "
                     f"{detail.get('seconds_adaptive')})"))
        bad.append(name)
    else:
        rows.append((name, "ok",
                     f"adaptive {value:.2f}x over static (floor "
                     f"{SKEWJOIN_MIN_SPEEDUP:.0f}x, adapted mid-query)"))
    return rows, bad


def _parse_artifact(path):
    """({metric: line-dict}, truncated) from any bench artifact shape: raw
    bench stdout (JSON lines), a single line, a list, or the driver's
    BENCH_r*.json wrapper ({"tail": "<stdout tail>", "parsed": <last
    line>}).  ``truncated`` is True for a wrapper whose stdout tail was
    cut mid-stream (its first kept line fails to parse): metrics absent
    from such an artifact fell off the tail — their absence says nothing
    about whether the benchmark ran."""
    with open(path, "r", encoding="utf-8") as f:
        text = f.read()
    lines = []
    truncated = False
    try:
        obj = json.loads(text)
    except ValueError:
        obj = None
    if isinstance(obj, dict) and "tail" in obj:
        tail_lines = [ln.strip() for ln in str(obj["tail"]).splitlines()
                      if ln.strip()]
        for i, ln in enumerate(tail_lines):
            try:
                lines.append(json.loads(ln))
            except ValueError:
                if i == 0:
                    truncated = True
        if not tail_lines:
            truncated = True
        if isinstance(obj.get("parsed"), dict):
            lines.append(obj["parsed"])
    elif isinstance(obj, dict) and "metric" in obj:
        lines = [obj]
    elif isinstance(obj, list):
        lines = obj
    else:
        for ln in text.splitlines():
            ln = ln.strip()
            if ln.startswith("{"):
                try:
                    lines.append(json.loads(ln))
                except ValueError:
                    pass
    return ({d["metric"]: d for d in lines
             if isinstance(d, dict) and "metric" in d}, truncated)


def load_metrics(path):
    """{metric: line-dict} from any bench artifact shape (see
    ``_parse_artifact``)."""
    return _parse_artifact(path)[0]


def _artifact_truncated(path):
    try:
        return _parse_artifact(path)[1]
    except (OSError, ValueError):
        return False


def _metric_ratio(d):
    """The comparable number: vs_baseline (normalized, unit-free across
    metrics) when present, else the raw value."""
    v = d.get("vs_baseline")
    return float(v if v is not None else d["value"])


def _critpath_of(d):
    detail = d.get("detail") or {}
    cp = detail.get("critpath")
    if cp:
        return cp
    # geomean line: nested per-query details
    return None


def _print_critpath_diff(metric, base_d, cur_d, out):
    pairs = []  # (heading, base_cp_or_None, cur_cp)
    cur_cp = _critpath_of(cur_d)
    if cur_cp:
        pairs.append((metric, _critpath_of(base_d), cur_cp))
    else:
        # geomean-style line: per-query details nested under the summary
        cur_queries = (cur_d.get("detail") or {}).get("queries") or {}
        base_queries = (base_d.get("detail") or {}).get("queries") or {}
        for qname, qd in sorted(cur_queries.items()):
            cp = (qd or {}).get("critpath")
            if cp:
                pairs.append((qname,
                              (base_queries.get(qname) or {}).get("critpath"),
                              cp))
    if not pairs:
        out.write(f"    (no critical-path data in the current run for "
                  f"{metric})\n")
        return
    for heading, base_cp, cp in pairs:
        out.write(f"    critical path [{heading}] "
                  f"(wall {cp.get('wall_s', 0) * 1e3:.1f}ms):\n")
        base_buckets = (base_cp or {}).get("buckets") or {}
        for k, v in (cp.get("buckets") or {}).items():
            if not v and not base_buckets.get(k):
                continue
            b = base_buckets.get(k)
            delta = (f" (baseline {b * 1e3:.1f}ms, "
                     f"{(v - b) * 1e3:+.1f}ms)" if b is not None else "")
            out.write(f"      {k:<10} {v * 1e3:>9.1f}ms{delta}\n")


# Warmup gates (lower-is-better, pulled from per-query bench detail):
# (relative growth allowed, absolute slack).  real_compiles gets integer
# slack so a 0 -> 2 wobble on a warm cache doesn't trip the gate, while a
# 0 -> 11 signature-space regression does.
WARMUP_GATES = {
    "warmup_seconds": (0.5, 1.0),
    "real_compiles_warmup": (0.5, 2.0),
}


def _warmup_details(metrics):
    """{qname: {warmup_seconds, real_compiles_warmup}} from a bench metric
    map — prefers the geomean line's nested per-query details, falls back
    to the per-query lines."""
    out = {}
    for d in metrics.values():
        detail = d.get("detail") or {}
        queries = detail.get("queries")
        if isinstance(queries, dict):
            for q, qd in queries.items():
                for k in WARMUP_GATES:
                    if qd and qd.get(k) is not None:
                        out.setdefault(q, {})[k] = float(qd[k])
    if out:
        return out
    for metric, d in metrics.items():
        if not metric.startswith("tpch_q"):
            continue
        q = metric.split("_")[1]
        detail = d.get("detail") or {}
        for k in WARMUP_GATES:
            if detail.get(k) is not None:
                out.setdefault(q, {})[k] = float(detail[k])
    return out


def check_warmup_gates(base, cur, current_not_comparable=False):
    """Per-query warmup regression rows: warmup_seconds and
    real_compiles_warmup must not grow past their gate (lower-is-better;
    MISSING from the current run = regression — a silently vanished warmup
    metric is exactly how warmup regressions would hide)."""
    b_w, c_w = _warmup_details(base), _warmup_details(cur)
    rows, regressed = [], []
    for q in sorted(b_w):
        for k, (thr, slack) in WARMUP_GATES.items():
            if k not in b_w[q]:
                continue
            name = f"warmup[{q}].{k}"
            b = b_w[q][k]
            c = (c_w.get(q) or {}).get(k)
            if c is None:
                if current_not_comparable:
                    rows.append((name, b, None, None, None, "not-run"))
                else:
                    rows.append((name, b, None, None, thr, "MISSING"))
                    regressed.append(name)
                continue
            bad = c > b * (1.0 + thr) + slack
            delta = (c - b) / b if b else None
            rows.append((name, b, c, delta, thr,
                         "REGRESSED" if bad else "ok"))
            if bad:
                regressed.append(name)
    return rows, regressed


# Memory gates (lower-is-better, from per-query detail.memory): relative
# growth allowed plus absolute slack.  64 MiB of slack absorbs allocator /
# padding-bucket wobble on small scale factors while a genuine doubling of
# a query's device high-water mark still trips.
MEMORY_GATES = {
    "peak_bytes": (0.5, 64 << 20),
}


def _memory_details(metrics):
    """{qname: {peak_bytes}} from a bench metric map — same sourcing rules
    as _warmup_details (geomean nested details first, per-query lines as
    fallback)."""
    out = {}
    for d in metrics.values():
        detail = d.get("detail") or {}
        queries = detail.get("queries")
        if isinstance(queries, dict):
            for q, qd in queries.items():
                mem = (qd or {}).get("memory") or {}
                for k in MEMORY_GATES:
                    if mem.get(k) is not None:
                        out.setdefault(q, {})[k] = float(mem[k])
    if out:
        return out
    for metric, d in metrics.items():
        if not metric.startswith("tpch_q"):
            continue
        q = metric.split("_")[1]
        mem = (d.get("detail") or {}).get("memory") or {}
        for k in MEMORY_GATES:
            if mem.get(k) is not None:
                out.setdefault(q, {})[k] = float(mem[k])
    return out


def check_memory_gates(base, cur, current_not_comparable=False):
    """Per-query peak-memory regression rows — the warmup-gate machinery
    applied to detail.memory (lower-is-better; MISSING = regression, since
    a vanished memory detail is how a footprint regression would hide).
    Baselines recorded before the memory plane existed carry no
    detail.memory and gate nothing."""
    b_m, c_m = _memory_details(base), _memory_details(cur)
    rows, regressed = [], []
    for q in sorted(b_m):
        for k, (thr, slack) in MEMORY_GATES.items():
            if k not in b_m[q]:
                continue
            name = f"memory[{q}].{k}"
            b = b_m[q][k]
            c = (c_m.get(q) or {}).get(k)
            if c is None:
                if current_not_comparable:
                    rows.append((name, b, None, None, None, "not-run"))
                else:
                    rows.append((name, b, None, None, thr, "MISSING"))
                    regressed.append(name)
                continue
            bad = c > b * (1.0 + thr) + slack
            delta = (c - b) / b if b else None
            rows.append((name, b, c, delta, thr,
                         "REGRESSED" if bad else "ok"))
            if bad:
                regressed.append(name)
    return rows, regressed


def check_regressions(base, cur, threshold=None, not_run_prefixes=()):
    """Compare {metric: line} maps; returns (report_rows, regressed_list).
    A metric present in the baseline but missing from the current run
    counts as regressed (a silently vanished benchmark is the regression
    mode this gate exists for) — EXCEPT metrics under ``not_run_prefixes``,
    which the current run's mode could not have produced (a fresh --check
    runs only the --measure section, so a baseline that also captured
    --service metrics must not trip on them)."""
    rows, regressed = [], []
    for metric in sorted(base):
        b = _metric_ratio(base[metric])
        thr = threshold if threshold is not None else \
            CHECK_THRESHOLDS.get(metric, CHECK_DEFAULT_THRESHOLD)
        if metric not in cur:
            if not_run_prefixes and metric.startswith(
                    tuple(not_run_prefixes)):
                rows.append((metric, b, None, None, None, "not-run"))
            else:
                rows.append((metric, b, None, None, thr, "MISSING"))
                regressed.append(metric)
            continue
        c = _metric_ratio(cur[metric])
        delta = (c - b) / b if b else 0.0
        bad = c < b * (1.0 - thr)
        rows.append((metric, b, c, delta, thr,
                     "REGRESSED" if bad else "ok"))
        if bad:
            regressed.append(metric)
    for metric in sorted(set(cur) - set(base)):
        rows.append((metric, None, _metric_ratio(cur[metric]), None,
                     None, "new"))
    return rows, regressed


def check_main(argv):
    import argparse
    import glob

    ap = argparse.ArgumentParser(
        prog="bench.py --check",
        description="Perf-regression gate: compare a bench run against a "
                    "baseline artifact; exit 1 on regression.")
    ap.add_argument("--against", default=None,
                    help="baseline artifact (default: newest BENCH_r*.json "
                         "next to bench.py)")
    ap.add_argument("--current", default=None,
                    help="compare this artifact instead of running the "
                         "bench now (file-vs-file mode)")
    ap.add_argument("--threshold", type=float, default=None,
                    help="override every per-metric relative threshold "
                         "(fraction, e.g. 0.2)")
    args = ap.parse_args(argv)

    against = args.against
    if against is None:
        here = os.path.dirname(os.path.abspath(__file__))
        cands = sorted(glob.glob(os.path.join(here, "BENCH_r*.json")))
        if not cands:
            sys.stderr.write("bench --check: no --against and no "
                             "BENCH_r*.json found\n")
            return 2
        against = cands[-1]
    try:
        base, base_truncated = _parse_artifact(against)
    except OSError as e:
        sys.stderr.write(f"bench --check: cannot read {against}: {e}\n")
        return 2
    if not base:
        sys.stderr.write(f"bench --check: no metrics in {against}\n")
        return 2

    not_run_prefixes = ()
    if args.current is not None:
        try:
            cur, cur_truncated = _parse_artifact(args.current)
        except OSError as e:
            sys.stderr.write(f"bench --check: cannot read "
                             f"{args.current}: {e}\n")
            return 2
        cur_src = args.current
        if cur_truncated:
            # which metrics survived the wrapper's 2000-byte tail is
            # arbitrary: gate only the intersection instead of failing
            # on lines that merely fell off the tail
            sys.stderr.write(
                f"bench --check: {args.current} is a truncated driver "
                "tail; baseline metrics absent from it report as "
                "not-run, not REGRESSED\n")
            not_run_prefixes = ("",)
    else:
        ensure_data()
        attempts = (["tpu", "tpu"] if probe_tpu() else []) + ["cpu"]
        lines = None
        for platform in attempts:
            lines = _run_child(platform, MEASURE_TIMEOUT)
            if lines is not None:
                break
        if lines is None:
            sys.stderr.write("bench --check: measurement failed\n")
            return 2
        cur = {d["metric"]: d for d in map(json.loads, lines)
               if "metric" in d}
        cur_src = "fresh run"
        # the fresh run executes only the --measure section: baseline
        # metrics from other modes (--service, --multichip) are "not run",
        # not missing
        not_run_prefixes = ("service_", "multichip_")
    if not cur:
        sys.stderr.write("bench --check: no current metrics\n")
        return 2

    rows, regressed = check_regressions(base, cur, args.threshold,
                                        not_run_prefixes=not_run_prefixes)
    # warmup gates (lower-is-better): a truncated current tail cannot carry
    # the per-query details, so absence there reports as not-run
    w_rows, w_regressed = check_warmup_gates(
        base, cur, current_not_comparable=bool(not_run_prefixes == ("",)))
    regressed += w_regressed
    # peak-memory gates (lower-is-better, same truncation rules)
    m_rows, m_regressed = check_memory_gates(
        base, cur, current_not_comparable=bool(not_run_prefixes == ("",)))
    regressed += m_regressed
    # bench honesty: recorded strategies must be runnable on the bench
    # platform; fresh runs must record them on the join/asof lines (a
    # truncated --current tail cannot carry details, so presence is only
    # required when we produced the lines ourselves)
    s_rows, s_bad = check_strategy_honesty(
        cur, require=(args.current is None))
    regressed += s_bad
    # EXPLAIN ANALYZE honesty: fresh join/asof lines must carry operator
    # actuals (detail.operators) — same presence discipline as strategy
    o_rows, o_bad = check_operators_presence(
        cur, require=(args.current is None))
    regressed += o_bad
    # device-efficiency honesty: fresh join/asof lines must carry the
    # devprof digest (detail.efficiency) — same presence discipline
    e_rows, e_bad = check_efficiency_presence(
        cur, require=(args.current is None))
    regressed += e_bad
    # whole-stage-fusion honesty: fresh join lines must show the fused
    # plan actually dispatched (detail.fused_stages >= 1)
    f_rows, f_bad = check_fused_stages_presence(
        cur, require=(args.current is None))
    regressed += f_bad
    # adaptive-planning gate: the fresh skewjoin line must exist, must have
    # actually adapted mid-query, and must clear SKEWJOIN_MIN_SPEEDUP
    k_rows, k_bad = check_skewjoin_gate(
        cur, require=(args.current is None))
    regressed += k_bad
    s_rows = s_rows + o_rows + e_rows + f_rows + k_rows
    out = sys.stdout
    out.write(f"bench --check: {cur_src} vs {against}\n")
    if base_truncated:
        out.write("  note: the baseline is a truncated driver tail — "
                  "metrics missing from IT are not gated at all\n")
    for metric, b, c, delta, thr, status in rows:
        b_s = f"{b:.4f}" if b is not None else "-"
        c_s = f"{c:.4f}" if c is not None else "-"
        d_s = f"{delta:+.1%}" if delta is not None else "-"
        t_s = f"(allow -{thr:.0%})" if thr is not None else ""
        out.write(f"  {status:>9}  {metric:<42} {b_s:>9} -> {c_s:>9} "
                  f"{d_s:>8} {t_s}\n")
        if status == "REGRESSED":
            _print_critpath_diff(metric, base[metric], cur[metric], out)
    for metric, b, c, delta, thr, status in w_rows + m_rows:
        b_s = f"{b:.4f}" if b is not None else "-"
        c_s = f"{c:.4f}" if c is not None else "-"
        d_s = f"{delta:+.1%}" if delta is not None else "-"
        t_s = f"(allow +{thr:.0%})" if thr is not None else ""
        out.write(f"  {status:>9}  {metric:<42} {b_s:>9} -> {c_s:>9} "
                  f"{d_s:>8} {t_s}\n")
    for name, status, why in s_rows:
        if status == "ok":
            out.write(f"  {status:>9}  {name}\n")
        else:
            out.write(f"  {status:>9}  {name}\n              {why}\n")
    if regressed:
        out.write(f"REGRESSION: {len(regressed)} metric(s) regressed "
                  f"beyond threshold: {', '.join(regressed)}\n")
        return 1
    out.write("clean: no metric regressed beyond its threshold\n")
    return 0


def _trend_slope(points):
    """Least-squares slope of [(x, y)] (per-round change); 0.0 for < 2
    points."""
    n = len(points)
    if n < 2:
        return 0.0
    mx = sum(x for x, _ in points) / n
    my = sum(y for _, y in points) / n
    den = sum((x - mx) ** 2 for x, _ in points)
    if den == 0:
        return 0.0
    return sum((x - mx) * (y - my) for x, y in points) / den


def trend_main(argv):
    """``bench.py --trend``: the cross-round view no single --check gives.
    Reads EVERY committed BENCH_r*.json, prints each metric's trajectory
    (vs_baseline ratio per round, least-squares slope) and exits 1 when a
    metric declines strictly monotonically over its last ``--window``
    CONSECUTIVE rounds — a slow leak each individual --check stayed inside
    its threshold on.  Truncated driver tails contribute the metrics they
    kept; a metric absent from a round is a gap, never a regression (which
    round survives a 2000-byte tail is arbitrary), and a decline spanning
    a gap doesn't trip the gate either — artifacts across gaps often span
    box re-baselines, so the change is not attributable round-to-round."""
    import argparse
    import glob

    ap = argparse.ArgumentParser(
        prog="bench.py --trend",
        description="Cross-round trajectory over committed BENCH_r*.json "
                    "artifacts; exit 1 on a monotone multi-round decline.")
    ap.add_argument("--dir", default=None,
                    help="artifact directory (default: next to bench.py)")
    ap.add_argument("--window", type=int, default=3,
                    help="consecutive recorded declines that count as a "
                         "regression (default 3)")
    args = ap.parse_args(argv)

    here = args.dir or os.path.dirname(os.path.abspath(__file__))
    paths = sorted(glob.glob(os.path.join(here, "BENCH_r*.json")))
    if len(paths) < 2:
        sys.stderr.write(f"bench --trend: need >= 2 BENCH_r*.json under "
                         f"{here}, found {len(paths)}\n")
        return 2
    rounds = []  # (label, {metric: ratio})
    for p in paths:
        label = os.path.basename(p)[len("BENCH_"):-len(".json")]
        try:
            metrics, _ = _parse_artifact(p)
        except (OSError, ValueError) as e:
            sys.stderr.write(f"bench --trend: skipping unreadable {p}: "
                             f"{e}\n")
            continue
        vals = {}
        for name, d in metrics.items():
            try:
                vals[name] = _metric_ratio(d)
            except (TypeError, ValueError, KeyError):
                pass
        rounds.append((label, vals))
    series = {}  # metric -> [(round_index, ratio)]
    for i, (_, vals) in enumerate(rounds):
        for name, v in vals.items():
            series.setdefault(name, []).append((i, v))

    out = sys.stdout
    labels = [lab for lab, _ in rounds]
    width = max(len(lab) for lab in labels)
    out.write(f"bench --trend: {len(rounds)} round(s) "
              f"({labels[0]}..{labels[-1]}), window={args.window}\n")
    regressed = []
    window = max(2, args.window)
    for name in sorted(series):
        pts = series[name]
        if len(pts) < 2:
            status = "sparse"  # one recorded round: no trajectory yet
        else:
            tail = pts[-window:]
            declining = (
                len(tail) >= window
                # consecutive rounds only: a decline across a recording
                # gap is not attributable to any single round
                and all(i2 == i1 + 1 for (i1, _), (i2, _)
                        in zip(tail, tail[1:]))
                and all(v1 > v2 for (_, v1), (_, v2)
                        in zip(tail, tail[1:])))
            status = "DECLINING" if declining else "ok"
            if declining:
                regressed.append(name)
        slope = _trend_slope(pts)
        by_round = dict(pts)
        cells = " ".join(
            f"{by_round[i]:>8.4f}" if i in by_round else f"{'-':>8}"
            for i in range(len(rounds)))
        out.write(f"  {status:>9}  {name:<42} {cells}  "
                  f"slope {slope:+.4f}/round\n")
    out.write("  rounds: " + " ".join(f"{lab:>8}" for lab in labels)
              + "\n")
    if regressed:
        out.write(f"TREND REGRESSION: {len(regressed)} metric(s) declined "
                  f"monotonically over their last {window} recorded "
                  f"round(s): {', '.join(regressed)}\n")
        return 1
    out.write("clean: no metric declined monotonically across rounds\n")
    return 0


# ---------------------------------------------------------------------------
# --multichip: timed N-device scaling line (mesh execution plane)
# ---------------------------------------------------------------------------
# Times every bench query once on ONE device (the embedded engine) and once
# across N devices (QuokkaContext(mesh=...): shard_map programs with
# all_to_all key shuffles, parallel/mesh_exec.py), and reports strong-scaling
# efficiency = (t_1 / t_N) / N per query.  On a real accelerator pod this is
# the ROADMAP's >= 0.6-at-8-chips line; on this box the 8 devices are
# XLA-forced host devices sharing the CPU cores, so the artifact carries
# forced_host + cpus so the number cannot be mistaken for chip scaling —
# the point is that the line is TIMED and the mesh path is exercised
# end-to-end, replacing five rounds of dry-run-only MULTICHIP artifacts.


def multichip_measure():
    """Child process: emits one JSON line per query + a geomean line."""
    import jax

    n = int(os.environ.get("QUOKKA_MULTICHIP_DEVICES", "8"))
    smoke = os.environ.get("QUOKKA_MULTICHIP_SMOKE") == "1"
    platform = jax.default_backend()
    if jax.device_count() < n:
        sys.stderr.write(
            f"bench --multichip: need {n} devices, have "
            f"{jax.device_count()} on {platform}\n")
        sys.exit(3)
    from quokka_tpu import QuokkaContext
    from quokka_tpu import obs as qk_obs
    from quokka_tpu.ops import strategy as kstrategy
    from quokka_tpu.parallel.mesh import make_mesh

    kstrategy.ensure_calibrated()
    paths = ensure_data()
    mesh = make_mesh(n)
    forced_host = platform == "cpu"
    builders = dict(BUILDERS)
    builders["asof"] = build_asof
    reps = 1 if smoke else 2
    effs, problems = [], []
    for qname, builder in builders.items():
        def run(ctx):
            q = builder(paths, ctx=ctx)
            t0 = time.time()
            q.collect()
            return time.time() - t0

        single = lambda: QuokkaContext(io_channels=3, exec_channels=2)  # noqa: E731
        run(single())  # warm: compiles + scan cache
        t1 = min(run(single()) for _ in range(reps))
        kstrategy.reset_used()
        mctx = QuokkaContext(mesh=mesh)
        run(mctx)  # warm the mesh programs
        warm_fallback = mctx.last_mesh_fallback
        snap0 = qk_obs.REGISTRY.snapshot()
        t_n = float("inf")
        for _ in range(reps):
            mctx = QuokkaContext(mesh=mesh)
            t_n = min(t_n, run(mctx))
        snap1 = qk_obs.REGISTRY.snapshot()
        host_syncs = int(snap1.get("shuffle.host_syncs", 0)
                         - snap0.get("shuffle.host_syncs", 0))
        fallback = mctx.last_mesh_fallback or warm_fallback
        speedup = t1 / t_n if t_n > 0 else 0.0
        eff = speedup / n
        effs.append(eff)
        if fallback:
            problems.append(f"{qname}: mesh fell back to the embedded "
                            f"engine ({fallback})")
        strategy_used = kstrategy.used_snapshot()
        if not strategy_used:
            problems.append(f"{qname}: no kernel strategy recorded")
        if host_syncs:
            problems.append(f"{qname}: {host_syncs} blocking host syncs on "
                            "the timed shuffle path")
        print(json.dumps({
            "metric": f"multichip_{qname}_scaling_efficiency",
            "value": round(eff, 4),
            "unit": "x",
            "vs_baseline": round(eff, 4),
            "detail": {
                "sf": SF, "platform": platform, "n_devices": n,
                "forced_host": forced_host, "cpus": os.cpu_count(),
                "seconds_1dev": round(t1, 4),
                "seconds_ndev": round(t_n, 4),
                "speedup": round(speedup, 4),
                "strategy": strategy_used,
                "shuffle_host_syncs": host_syncs,
                "mesh_fallback": fallback,
            },
        }))
        sys.stdout.flush()
    geomean = math.exp(sum(math.log(max(e, 1e-9)) for e in effs) / len(effs))
    print(json.dumps({
        "metric": "multichip_scaling_efficiency_geomean",
        "value": round(geomean, 4),
        "unit": "x",
        "vs_baseline": round(geomean, 4),
        "detail": {"sf": SF, "platform": platform, "n_devices": n,
                   "forced_host": forced_host, "cpus": os.cpu_count(),
                   "queries": list(builders),
                   "strategy_matrix": kstrategy.choices()},
    }))
    sys.stdout.flush()
    if problems:
        for p in problems:
            sys.stderr.write(f"bench --multichip: {p}\n")
        # a fallback/untracked-strategy/host-sync line is not a timed
        # multichip measurement — fail loudly rather than ship it
        sys.exit(4)


def multichip_main(argv):
    import argparse

    ap = argparse.ArgumentParser(
        prog="bench.py --multichip",
        description="Timed N-device scaling bench over the mesh execution "
                    "plane; writes a MULTICHIP artifact with per-query "
                    "scaling efficiency.")
    ap.add_argument("--devices", type=int,
                    default=int(os.environ.get("QUOKKA_MULTICHIP_DEVICES",
                                               "8")))
    ap.add_argument("--smoke", action="store_true",
                    help="single timed rep + assertions (CI)")
    ap.add_argument("--out",
                    default=os.environ.get("QUOKKA_MULTICHIP_OUT")
                    or os.path.join(bench_out_dir(),
                                    "MULTICHIP_timed.json"))
    args = ap.parse_args(argv)
    ensure_data()
    env = dict(os.environ)
    env["QUOKKA_MULTICHIP_DEVICES"] = str(args.devices)
    if args.smoke:
        env["QUOKKA_MULTICHIP_SMOKE"] = "1"
    # real chips when the probe sees an accelerator (the child still checks
    # the device COUNT and exits 3 if the pod is too small); forced-host
    # XLA devices otherwise
    attempts = ["tpu"] if probe_tpu() else []
    attempts.append("cpu")
    r = None
    for platform in attempts:
        child_env = dict(env)
        if platform == "cpu":
            child_env["QUOKKA_BENCH_FORCE_CPU"] = "1"
            flags = child_env.get("XLA_FLAGS", "")
            if "xla_force_host_platform_device_count" not in flags:
                child_env["XLA_FLAGS"] = (
                    flags
                    + f" --xla_force_host_platform_device_count={args.devices}"
                ).strip()
        try:
            r = subprocess.run(
                [sys.executable, os.path.abspath(__file__),
                 "--multichip-measure"],
                timeout=MEASURE_TIMEOUT, capture_output=True, text=True,
                env=child_env,
            )
        except subprocess.TimeoutExpired:
            sys.stderr.write("bench --multichip: child exceeded "
                             f"{MEASURE_TIMEOUT}s\n")
            continue
        if r.returncode == 0:
            break
        sys.stderr.write(f"bench --multichip [{platform}] child "
                         f"rc={r.returncode}:\n{r.stderr[-2000:]}\n")
    if r is None or r.returncode != 0:
        sys.stderr.write("bench --multichip: all attempts failed\n")
        return 1
    if r.stderr:
        sys.stderr.write(r.stderr[-4000:])
    lines = []
    for ln in r.stdout.strip().splitlines():
        ln = ln.strip()
        if ln.startswith("{"):
            try:
                lines.append(json.loads(ln))
            except ValueError:
                pass
    for d in lines:
        print(json.dumps(d))
    if not any(d.get("metric") == "multichip_scaling_efficiency_geomean"
               for d in lines):
        sys.stderr.write("bench --multichip: no geomean line produced\n")
        return 1
    artifact = {
        "n_devices": args.devices,
        "timed": True,
        "lines": lines,
    }
    try:
        with open(args.out, "w", encoding="utf-8") as f:
            json.dump(artifact, f, indent=2)
        sys.stderr.write(f"bench --multichip: artifact written to "
                         f"{args.out}\n")
    except OSError as e:
        sys.stderr.write(f"bench --multichip: cannot write {args.out}: "
                         f"{e}\n")
        return 1
    return 0


def main():
    ensure_data()
    attempts = []
    if probe_tpu():
        attempts = ["tpu", "tpu"]  # one retry on a mid-run wedge
    else:
        sys.stderr.write("bench: TPU unavailable after probe retries\n")
    attempts.append("cpu")  # LOUD fallback, flagged in the JSON
    for platform in attempts:
        if platform == "cpu":
            sys.stderr.write("bench: falling back to CPU — NOT a TPU number\n")
        lines = _run_child(platform, MEASURE_TIMEOUT)
        if lines is not None:
            print("\n".join(lines))
            return
    sys.stderr.write("bench: all measurement attempts failed\n")
    sys.exit(1)


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "--measure":
        # a full TPC-H run records far more than the 4096-event default
        # ring; size it so the critical-path profile keeps the whole last
        # timed run (set BEFORE the first quokka_tpu import instantiates
        # the recorder)
        os.environ.setdefault("QK_TRACE_BUFFER", "262144")
        if os.environ.get("QUOKKA_BENCH_FORCE_CPU"):
            import jax

            try:
                jax.config.update("jax_platforms", "cpu")
            except Exception:
                pass
        measure(ensure_data())
    elif len(sys.argv) > 1 and sys.argv[1] == "--service":
        # concurrent-service mode runs in-process (no TPU wedge supervision:
        # it is the CI smoke + local measurement path; CPU via JAX_PLATFORMS).
        # Failure mode is an exception (wedge -> QueryStallTimeout, failed
        # query -> its error, empty smoke result -> RuntimeError): any of
        # them exits nonzero
        measure_service(ensure_data(), smoke="--smoke" in sys.argv[2:])
    elif len(sys.argv) > 1 and sys.argv[1] == "--multichip-measure":
        # runs INSIDE the supervised child: the parent sized the forced-host
        # device pool (XLA_FLAGS) / picked the platform before jax init
        if os.environ.get("QUOKKA_BENCH_FORCE_CPU"):
            import jax

            try:
                jax.config.update("jax_platforms", "cpu")
            except Exception:
                pass
        multichip_measure()
    elif len(sys.argv) > 1 and sys.argv[1] == "--multichip":
        # timed N-device scaling line over the mesh plane (forced-host
        # devices on a plain box, real chips when available); writes the
        # MULTICHIP artifact and exits nonzero on fallback/untimed lines
        sys.exit(multichip_main(sys.argv[2:]))
    elif len(sys.argv) > 1 and sys.argv[1] == "--check":
        # perf-regression gate: fresh run (or --current file) vs the
        # newest BENCH_r*.json (or --against); exit 1 on regression with
        # the regressed queries' critical-path diffs printed
        sys.exit(check_main(sys.argv[2:]))
    elif len(sys.argv) > 1 and sys.argv[1] == "--trend":
        # cross-round trajectory over every committed BENCH_r*.json; exit 1
        # when a metric declined monotonically across the last N rounds —
        # the slow leak each individual --check stayed under threshold on
        sys.exit(trend_main(sys.argv[2:]))
    elif len(sys.argv) > 1 and sys.argv[1] == "--chaos":
        # seeded mixed-fault soak (the chaos plane, quokka_tpu/chaos):
        # bit-exact-under-injection is a robustness benchmark, so it rides
        # the bench entry point too; extra args pass through (--runs/--seed)
        from quokka_tpu.chaos.soak import main as chaos_main

        sys.exit(chaos_main(sys.argv[2:]))
    else:
        main()
