"""Benchmark: TPC-H Q1 through the full engine on the local accelerator.

Prints ONE JSON line:
  {"metric": "tpch_q1_scan_gbps_per_chip", "value": N, "unit": "GB/s",
   "vs_baseline": N / 0.654}

Baseline derivation (BASELINE.md): the reference's captured TPC-H run shows
Q1 ~= 9.56 s average at SF100 on 4 workers (blocking-runtime:27,53,79).  SF100
lineitem as Parquet is ~25 GB, so the reference sustains ~25 / (9.56 * 4)
~= 0.654 GB/s of Parquet per worker node.  Our metric is the same quantity per
TPU chip: lineitem Parquet bytes / Q1 wall-seconds (steady-state run, compile
cached).

Robustness: the tunneled dev TPU runtime can WEDGE mid-RPC (a blocked
tcp_recvmsg that never returns), which would hang this process forever.  All
device work therefore runs in a SUPERVISED CHILD process with a hard timeout:
probe -> measure on TPU; on wedge/timeout the child is killed and the
measurement retries once, then falls back to CPU -- loudly (platform +
tpu_fallback_to_cpu fields; the value still parses but cannot be mistaken for
a TPU number).
"""

import json
import os
import subprocess
import sys
import time

BASELINE_GBPS_PER_WORKER = 0.654

SF = float(os.environ.get("QUOKKA_BENCH_SF", "1.0"))
CACHE = os.environ.get("QUOKKA_BENCH_CACHE", "/tmp/quokka_tpu_bench")
# generous: first compile of the full kernel set over the remote-compile
# tunnel is minutes; a healthy steady-state run is seconds
MEASURE_TIMEOUT = int(os.environ.get("QUOKKA_BENCH_TIMEOUT", "1500"))


def ensure_data():
    os.makedirs(CACHE, exist_ok=True)
    path = os.path.join(CACHE, f"lineitem_sf{SF}.parquet")
    if not os.path.exists(path):
        sys.path.insert(0, os.path.join(os.path.dirname(__file__), "tests"))
        import tpch_data

        tables = tpch_data.generate(sf=SF, seed=42)
        import pyarrow.parquet as pq

        pq.write_table(tables["lineitem"], path, row_group_size=1 << 20)
    return path


Q1_COLS = [
    "l_returnflag",
    "l_linestatus",
    "l_quantity",
    "l_extendedprice",
    "l_discount",
    "l_tax",
    "l_shipdate",
]

Q1_AGGS = (
    "sum(l_quantity) as sum_qty, "
    "sum(l_extendedprice) as sum_base_price, "
    "sum(l_extendedprice * (1 - l_discount)) as sum_disc_price, "
    "sum(l_extendedprice * (1 - l_discount) * (1 + l_tax)) as sum_charge, "
    "avg(l_quantity) as avg_qty, "
    "avg(l_extendedprice) as avg_price, "
    "avg(l_discount) as avg_disc, "
    "count(*) as count_order"
)


def run_q1(path):
    from quokka_tpu import QuokkaContext

    ctx = QuokkaContext(io_channels=3, exec_channels=2)
    q = (
        ctx.read_parquet(path, columns=Q1_COLS)
        .filter_sql("l_shipdate <= date '1998-12-01' - interval '90' day")
        .groupby(["l_returnflag", "l_linestatus"])
        .agg_sql(Q1_AGGS)
    )
    t0 = time.time()
    df = q.collect()
    return time.time() - t0, df


def measure(path):
    """The full measurement (runs inside the supervised child).  Emits one
    JSON line on fd 1 and exits 0."""
    import jax

    platform = jax.default_backend()
    nbytes = os.path.getsize(path)
    # warm-up run compiles the kernel set; measured runs reflect steady state
    warm, df = run_q1(path)
    from quokka_tpu.runtime import scancache

    # cold = compile warm but scan (buffer-pool) cache empty: pays parquet
    # decode + host encode + h2d transfer every batch
    scancache.clear()
    cold, df = run_q1(path)
    # warm steady state = the buffer-pool regime (hot segments device-resident,
    # the reference analog being OS page cache + executor-local reuse); this is
    # the headline because repeated analytics over hot tables is the
    # steady-state the engine is designed for
    times = []
    for _ in range(3):
        t, df = run_q1(path)
        times.append(t)
    t = min(times)
    assert len(df) == 6, df
    gbps = nbytes / t / 1e9
    cold_gbps = nbytes / cold / 1e9
    result = {
        "metric": "tpch_q1_scan_gbps_per_chip",
        "value": round(gbps, 4),
        "unit": "GB/s",
        "vs_baseline": round(gbps / BASELINE_GBPS_PER_WORKER, 4),
        "detail": {
            "sf": SF,
            "parquet_bytes": nbytes,
            "q1_seconds_warm": round(t, 4),
            "q1_seconds_all": [round(x, 4) for x in times],
            "q1_seconds_cold_scan": round(cold, 4),
            "cold_scan_gbps": round(cold_gbps, 4),
            "cold_vs_baseline": round(cold_gbps / BASELINE_GBPS_PER_WORKER, 4),
            "warmup_seconds": round(warm, 4),
            "platform": platform,
            "tpu_fallback_to_cpu": platform == "cpu",
        },
    }
    print(json.dumps(result))


def probe_tpu(attempts: int = 2, timeout: int = 150, backoff: int = 20) -> bool:
    """Check the TPU backend from a SUBPROCESS so a wedged tunnel (which hangs
    jax.devices() indefinitely) can't hang the bench itself.  Bounded retries
    with backoff; False means the tunnel is down after all attempts."""
    probe = (
        "import jax, jax.numpy as jnp;"
        "d = jax.devices();"
        "(jnp.ones((64, 64)) @ jnp.ones((64, 64))).block_until_ready();"
        "print('ok', d[0].platform)"
    )
    for i in range(attempts):
        try:
            r = subprocess.run(
                [sys.executable, "-c", probe],
                timeout=timeout, capture_output=True, text=True,
            )
            if r.returncode == 0 and "ok" in r.stdout:
                platform = r.stdout.strip().split()[-1].lower()
                if platform not in ("cpu",):
                    return True
                # JAX silently picked CPU (plugin missing): that is NOT a TPU
                sys.stderr.write(
                    f"bench: probe initialized platform {platform!r}, not TPU\n"
                )
                return False
            sys.stderr.write(
                f"bench: TPU probe {i + 1}/{attempts} failed rc={r.returncode}: "
                f"{(r.stderr or r.stdout)[-200:]}\n"
            )
        except subprocess.TimeoutExpired:
            sys.stderr.write(f"bench: TPU probe {i + 1}/{attempts} timed out\n")
        if i < attempts - 1:
            time.sleep(backoff)
    return False


def _run_child(path: str, platform: str, timeout: int):
    """Run measure() in a child; returns the JSON line or None on wedge."""
    env = dict(os.environ)
    if platform == "cpu":
        env["QUOKKA_BENCH_FORCE_CPU"] = "1"
    try:
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--measure", path],
            timeout=timeout, capture_output=True, text=True, env=env,
        )
    except subprocess.TimeoutExpired:
        sys.stderr.write(
            f"bench: measurement child exceeded {timeout}s (wedged tunnel?)\n"
        )
        return None
    if r.returncode != 0:
        sys.stderr.write(f"bench: measurement child rc={r.returncode}:\n"
                         f"{r.stderr[-2000:]}\n")
        return None
    for line in reversed(r.stdout.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            return line
    sys.stderr.write(f"bench: child produced no JSON: {r.stdout[-500:]}\n")
    return None


def main():
    path = ensure_data()
    attempts = []
    if probe_tpu():
        attempts = ["tpu", "tpu"]  # one retry on a mid-run wedge
    else:
        sys.stderr.write("bench: TPU unavailable after probe retries\n")
    attempts.append("cpu")  # LOUD fallback, flagged in the JSON
    for platform in attempts:
        if platform == "cpu":
            sys.stderr.write("bench: falling back to CPU — NOT a TPU number\n")
        line = _run_child(path, platform, MEASURE_TIMEOUT)
        if line is not None:
            print(line)
            return
    sys.stderr.write("bench: all measurement attempts failed\n")
    sys.exit(1)


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "--measure":
        if os.environ.get("QUOKKA_BENCH_FORCE_CPU"):
            import jax

            try:
                jax.config.update("jax_platforms", "cpu")
            except Exception:
                pass
        measure(sys.argv[2])
    else:
        main()
